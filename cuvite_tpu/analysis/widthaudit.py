"""Tier 6 (dynamic half) — the width audit (W001-W003).

The static half (analysis/widthcheck.py: R026-R028) bounds index
arithmetic symbolically; this module traces the REAL device-path
entries — the solo sort/bucketed/fused phase programs, the batched
execute, and the device coarsen+coalesce — at Friendster-class and
R-MAT scale-28 slab shapes via ``jax.make_jaxpr``/``jax.eval_shape``
with ZERO device bytes allocated (every program stages abstractly
under omnistaging; a live-buffer spy pins the invariant), and grades
three properties the AST walk cannot:

  * **W001 — index-carrying buffer width.**  Every ``iota`` /
    ``cumsum``-class equation in the traced jaxprs whose output is an
    integer buffer must be wide enough for the extent it indexes: an
    int32 run-id cumsum over a 2^32-row slab WILL wrap (wrong labels,
    not a crash).  The capacity law (``index_bits``) comes from
    ``tools/width_budget.json``.

  * **W002 — fallbacks actually selected at the boundary.**  Each
    eligibility predicate is probed at its widest-legal shape, one
    step past, and (for the packed sort) under forced x64:

      - the packed single-key int32 sort at ``kbits+sbits == 31`` and
        the lexicographic two-key fallback at ``== 32`` (the
        segment.py contract), with the int64 single-key under
        ``jax_enable_x64``;
      - ``coalesce_engine`` honoring its nv ceiling and the ds32
        degrade even when the env knob demands the dense engine;
      - the ``SLAB_NE_MAX`` / ``FLAT_NV_MAX`` raise-guards actually
        raising one step past the ceiling (fail-loud, never wrap);
      - ``_accum_name`` switching to ds32 exactly at
        ``DS_MIN_TOTAL_WEIGHT``.

    Additionally, any traced entry at an ineligible workload
    (``kbits+sbits > 31``) that still contains an int32 single-key
    sort is a conviction — the fallback was NOT selected.

  * **W003 — audit integrity (the M000 precedent).**  A crashing
    entry, an unreadable/mismatched budget manifest (its laws must
    equal the code constants and the registry's declared max
    workload), or a nonzero live-buffer delta after tracing each
    FAILS CLOSED as a finding, never as a silent skip.

Dynamic results are NEVER cached (the concheck/meshcheck precedent):
findings anchor on ``<width:entry>`` pseudo-paths outside the lint
cache.  ``tools/width_audit.py`` is the CLI; tests/test_widthcheck.py
runs the same audit in-process.
"""

from __future__ import annotations

import contextlib
import gc
import json
import os

import numpy as np

from cuvite_tpu.analysis.engine import Finding
from cuvite_tpu.analysis.widthcheck import INT32_MAX, MAX_WORKLOAD

BUDGET_VERSION = 1

DEFAULT_BUDGET_REL = os.path.join("tools", "width_budget.json")

# The fixed classes of the small entries: batched serving multiplexes
# B tenants of modest graphs; the dense coalesce is only ever offered
# classes within its flat-key ceiling.
BATCHED_NV = 1 << 12
BATCHED_NE = 1 << 14
DENSE_NV = 1 << 12
DENSE_NE = 1 << 16

# Jaxpr primitives whose integer outputs carry INDICES of their
# operated extent (run ids, positions, slot numbers).  reduce_sum is
# deliberately absent: its addends are unbounded from the jaxpr alone
# and the static tier (R028) already partitions that class.
_INDEX_PRIMS = ("iota", "cumsum", "cummax", "cummin")


def _wfind(rule: str, entry: str, message: str,
           snippet: str = "") -> Finding:
    return Finding(rule=rule, severity="high", path=f"<width:{entry}>",
                   line=0, message=message, snippet=snippet)


@contextlib.contextmanager
def _env(name: str, value: str | None):
    prior = os.environ.get(name)
    try:
        if value is None:
            os.environ.pop(name, None)
        else:
            os.environ[name] = value
        yield
    finally:
        if prior is None:
            os.environ.pop(name, None)
        else:
            os.environ[name] = prior


def live_device_bytes() -> int:
    """Total bytes of live device buffers — the spy the zero-allocation
    pin reads before and after the trace sweep."""
    import jax

    return sum(int(getattr(x, "nbytes", 0)) for x in jax.live_arrays())


# ---------------------------------------------------------------------------
# Workload shapes (derived from the registry, the single source).


def shard_plan(ne_pad: int) -> int:
    """Smallest power-of-two shard count that brings the per-shard slab
    under SLAB_NE_MAX — how the billion-edge path actually arrives."""
    from cuvite_tpu.ops.segment import SLAB_NE_MAX

    s = 1
    while ne_pad // s > SLAB_NE_MAX:
        s *= 2
    return s


def audit_workloads() -> dict:
    """{name: {nv_pad, ne_pad, shards, ne_shard}} for the certification
    shapes: the largest REAL dataset class (Friendster) and the R-MAT
    scale-28 law — both derived from workloads/registry.py, never
    restated here."""
    from cuvite_tpu.core.types import next_pow2
    from cuvite_tpu.workloads import registry

    out = {}
    fr = registry.DATASETS["friendster"]
    pairs = [("friendster", fr.width_nv, fr.width_ne)]
    s_nv, s_ne = registry.rmat_scale_law(registry.RMAT_SCALE_MAX)
    pairs.append((f"rmat_s{registry.RMAT_SCALE_MAX}", s_nv, s_ne))
    for name, nv, ne in pairs:
        nv_pad, ne_pad = next_pow2(nv), next_pow2(ne)
        s = shard_plan(ne_pad)
        out[name] = {"nv_pad": nv_pad, "ne_pad": ne_pad, "shards": s,
                     "ne_shard": ne_pad // s}
    return out


# ---------------------------------------------------------------------------
# Jaxpr extraction: W001 walk + sort facts.


def _walk_eqns(jaxpr):
    from cuvite_tpu.analysis.jaxpr_audit import _sub_jaxprs

    stack = [jaxpr]
    while stack:
        jx = stack.pop()
        core = getattr(jx, "jaxpr", jx)
        for eqn in getattr(core, "eqns", ()):
            yield eqn
            for key in eqn.params:
                stack.extend(_sub_jaxprs(eqn.params[key]))


def index_width_findings(jaxpr, entry: str, index_bits: int) -> list:
    """W001: every index-carrying integer buffer in the trace must be
    wide enough for its operated extent."""
    out = []
    for eqn in _walk_eqns(jaxpr):
        name = eqn.primitive.name
        if name not in _INDEX_PRIMS:
            continue
        for v in eqn.outvars:
            aval = getattr(v, "aval", None)
            if aval is None or not getattr(aval, "shape", ()):
                continue
            dt = np.dtype(aval.dtype)
            if dt.kind not in "iu" or dt.itemsize * 8 > index_bits:
                continue
            cap = 2 ** (dt.itemsize * 8 - 1) - 1
            if name == "iota":
                dim = eqn.params.get("dimension", 0)
                extent = int(aval.shape[dim])
                worst = extent - 1  # iota's max emitted value
            else:
                ax = eqn.params.get("axis", 0)
                extent = int(aval.shape[ax])
                worst = extent    # a 0/1-mask cumsum can reach extent
            if worst > cap:
                out.append(_wfind(
                    "W001", entry,
                    f"'{entry}' traces an {dt.name} '{name}' over a "
                    f"{extent}-extent axis (max index {worst} > "
                    f"{cap}): the buffer is narrower than the "
                    f"manifest's index law ({index_bits} bits) allows "
                    "for this shape — a silent wraparound producing "
                    "wrong run ids/labels, not a crash",
                    snippet=name))
    return out


def sort_facts(jaxpr) -> list:
    """[(num_keys, key_dtype_name, key_ndim)] for every lax.sort
    equation in the trace — the observable that proves which comparator
    was selected.  ``key_ndim`` separates the 1-D edge-slab sort (the
    kbits+sbits pack under audit) from the bucketed row-argmax's 2-D
    ``(cmat << bits) | iota`` sort, which packs over the ROW width
    under its own ``(id_bound << bits) <= 2^31`` predicate."""
    facts = []
    for eqn in _walk_eqns(jaxpr):
        if eqn.primitive.name != "sort":
            continue
        nk = int(eqn.params.get("num_keys", 1))
        key = eqn.invars[0] if eqn.invars else None
        dt = np.dtype(key.aval.dtype).name if key is not None else "?"
        nd = len(getattr(key.aval, "shape", ())) if key is not None \
            else 0
        facts.append((nk, dt, nd))
    return facts


# ---------------------------------------------------------------------------
# Entries: each traces ONE real device-path program at (nv_pad,
# ne_shard) and returns its jaxpr.  All callables are the raw
# (unjitted) functions so nothing lands in the global jit caches.


def _sds(shape, dtype):
    import jax

    return jax.ShapeDtypeStruct(shape, dtype)


def _accum_for(ne: int):
    from cuvite_tpu.louvain.driver import _accum_name

    name = _accum_name(np.float32, float(ne), ne)
    return None if name == "float32" else name


def _trace_solo_sort(nv: int, ne: int):
    import jax
    import jax.numpy as jnp

    from cuvite_tpu.louvain.step import louvain_step_local

    def entry(src, dst, w, comm, vdeg, constant):
        out = louvain_step_local(src, dst, w, comm, vdeg, constant,
                                 nv_total=nv, axis_name=None,
                                 accum_dtype=_accum_for(ne))
        return out.target, out.modularity, out.n_moved

    return jax.make_jaxpr(entry)(
        _sds((ne,), jnp.int32), _sds((ne,), jnp.int32),
        _sds((ne,), jnp.float32), _sds((nv,), jnp.int32),
        _sds((nv,), jnp.float32), _sds((), jnp.float32))


def _trace_solo_fused(nv: int, ne: int):
    import jax
    import jax.numpy as jnp

    from cuvite_tpu.louvain.fused import fused_phase

    def entry(src, dst, w, constant):
        return fused_phase(src, dst, w, constant, 1e-6, nv_pad=nv,
                           accum_dtype=_accum_for(ne))

    return jax.make_jaxpr(entry)(
        _sds((ne,), jnp.int32), _sds((ne,), jnp.int32),
        _sds((ne,), jnp.float32), _sds((), jnp.float32))


def _trace_solo_bucketed(nv: int, ne: int):
    import jax
    import jax.numpy as jnp

    from cuvite_tpu.louvain.bucketed import bucketed_step

    # A synthetic-but-representative plan: three degree classes and a
    # heavy residual, rows covering the vertex space.  Only SHAPES
    # matter here; the plan-build host path has its own tier-1 tests.
    widths = (4, 16, 64)
    nb = max(nv // 8, 1)
    buckets = tuple(
        (_sds((nb,), jnp.int32), _sds((nb, d), jnp.int32),
         _sds((nb, d), jnp.float32))
        for d in widths)
    heavy = (_sds((ne // 4,), jnp.int32), _sds((ne // 4,), jnp.int32),
             _sds((ne // 4,), jnp.float32))

    def entry(bucket_arrays, heavy_arrays, self_loop, comm, vdeg,
              constant):
        return bucketed_step(bucket_arrays, heavy_arrays, self_loop,
                             comm, vdeg, constant, nv_total=nv,
                             sentinel=np.iinfo(np.int32).max,
                             accum_dtype=_accum_for(ne))

    return jax.make_jaxpr(entry)(
        buckets, heavy, _sds((nv,), jnp.float32), _sds((nv,), jnp.int32),
        _sds((nv,), jnp.float32), _sds((), jnp.float32))


def _trace_batched(nv: int, ne: int):
    import jax
    import jax.numpy as jnp

    from cuvite_tpu.louvain.fused import fused_phase
    from cuvite_tpu.workloads.registry import BATCH_MAX

    b, tnv, tne = BATCH_MAX, BATCHED_NV, BATCHED_NE

    def one(src, dst, w, constant):
        return fused_phase(src, dst, w, constant, 1e-6, nv_pad=tnv,
                           accum_dtype=None)

    def entry(src, dst, w, constant):
        return jax.vmap(one)(src, dst, w, constant)

    return jax.make_jaxpr(entry)(
        _sds((b, tne), jnp.int32), _sds((b, tne), jnp.int32),
        _sds((b, tne), jnp.float32), _sds((b,), jnp.float32))


def _trace_coarsen(nv: int, ne: int):
    import jax
    import jax.numpy as jnp

    from cuvite_tpu.coarsen.device import device_coarsen_slab

    def entry(src, dst, w, comm, real_mask):
        return device_coarsen_slab(src, dst, w, comm, real_mask,
                                   nv_pad=nv,
                                   accum_dtype=_accum_for(ne),
                                   coalesce="sort")

    return jax.make_jaxpr(entry)(
        _sds((ne,), jnp.int32), _sds((ne,), jnp.int32),
        _sds((ne,), jnp.float32), _sds((nv,), jnp.int32),
        _sds((nv,), jnp.bool_))


def _trace_coalesce_dense(nv: int, ne: int):
    import jax
    import jax.numpy as jnp

    from cuvite_tpu.kernels.seg_coalesce import seg_coalesce_xla

    dnv, dne = DENSE_NV, DENSE_NE

    def entry(src, dst, w):
        return seg_coalesce_xla(src, dst, w, nv_pad=dnv)

    return jax.make_jaxpr(entry)(
        _sds((dne,), jnp.int32), _sds((dne,), jnp.int32),
        _sds((dne,), jnp.float32))


# name -> (tracer, sorts_expected): ``sorts_expected`` marks entries
# whose slab rides sort_edges_by_vertex_comm, where the ineligible-
# shape fallback check (no int32 single-key sort) applies.
ENTRIES = {
    "solo_sort_step": (_trace_solo_sort, True),
    "solo_fused_phase": (_trace_solo_fused, False),
    "solo_bucketed_step": (_trace_solo_bucketed, True),
    "batched_execute": (_trace_batched, False),
    "coarsen_coalesce": (_trace_coarsen, True),
    "coalesce_dense": (_trace_coalesce_dense, False),
}


def _pack_eligible(nv_pad: int, pack_bits: int) -> bool:
    """The segment.py packed-sort predicate at the step's bounds
    (src_bound = nv_local + 1, key_bound = nv_total)."""
    kbits = max(nv_pad - 1, 1).bit_length()
    sbits = max(nv_pad, 1).bit_length()
    return kbits + sbits <= pack_bits


# ---------------------------------------------------------------------------
# W002: boundary probes.


def boundary_probes(laws: dict) -> tuple:
    """(findings, facts) from probing every eligibility predicate at
    its widest-legal shape, one step past, and the forced-64 mode."""
    import jax
    import jax.numpy as jnp

    from cuvite_tpu.kernels import seg_coalesce
    from cuvite_tpu.louvain.driver import DS_MIN_TOTAL_WEIGHT, _accum_name
    from cuvite_tpu.ops import segment

    findings: list = []
    facts: dict = {}
    pack_bits = int(laws.get("pack_bits", 31))
    ne = 1 << 10

    def sort_probe(kb, sb):
        def fn(src, ckey, w):
            return segment.sort_edges_by_vertex_comm(
                src, ckey, w, src_bound=1 << sb, key_bound=1 << kb)

        return sort_facts(jax.make_jaxpr(fn)(
            _sds((ne,), jnp.int32), _sds((ne,), jnp.int32),
            _sds((ne,), jnp.float32)))

    # Widest-legal: kbits+sbits == pack_bits -> ONE int32 key.
    legal = sort_probe(pack_bits - 15, 15)
    facts["sort_widest_legal"] = legal
    if (1, "int32", 1) not in legal:
        findings.append(_wfind(
            "W002", "packed_sort",
            f"at kbits+sbits == {pack_bits} (the widest legal packing) "
            f"the sort traced {legal}, not the single-key int32 packed "
            "comparator — the 4-5x fast path regressed at its own "
            "boundary"))
    # One past: the lexicographic two-key fallback, never int32 packed.
    past = sort_probe(pack_bits - 14, 15)
    facts["sort_one_past"] = past
    if any(nk == 1 and dt == "int32" for nk, dt, _nd in past):
        findings.append(_wfind(
            "W002", "packed_sort",
            f"at kbits+sbits == {pack_bits + 1} the sort still traced "
            f"an int32 single-key comparator ({past}): the packed key "
            "bleeds into the sign bit and rows sort to the FRONT — the "
            "eligibility predicate is not selecting the fallback"))
    elif not any(nk == 2 for nk, dt, _nd in past):
        findings.append(_wfind(
            "W002", "packed_sort",
            f"at kbits+sbits == {pack_bits + 1} no two-key "
            f"lexicographic sort appeared ({past}): the fallback "
            "comparator is missing"))
    # Forced-64: the same ineligible shape packs into ONE int64 key.
    x64_prior = jax.config.jax_enable_x64
    try:
        jax.config.update("jax_enable_x64", True)
        forced = sort_probe(pack_bits - 14, 15)
    finally:
        jax.config.update("jax_enable_x64", x64_prior)
    facts["sort_forced_64"] = forced
    if (1, "int64", 1) not in forced:
        findings.append(_wfind(
            "W002", "packed_sort",
            f"under jax_enable_x64 at kbits+sbits == {pack_bits + 1} "
            f"the sort traced {forced}, not the single-key int64 pack "
            "— the oracle mode lost the wide fast path"))

    # coalesce_engine: the env knob must NOT override the nv ceiling or
    # the ds32 degrade (ineligible classes go to 'sort' in every mode).
    cap = int(laws.get("coalesce_max_nv", 32768))
    with _env("CUVITE_SEG_COALESCE", "xla"):
        eligible = seg_coalesce.coalesce_engine(DENSE_NV)
        over = seg_coalesce.coalesce_engine(cap * 2)
        ds = seg_coalesce.coalesce_engine(DENSE_NV, accum_dtype="ds32")
    facts["coalesce"] = {"eligible": eligible, "over_cap": over,
                         "ds32": ds}
    if eligible != "xla":
        findings.append(_wfind(
            "W002", "coalesce_engine",
            f"CUVITE_SEG_COALESCE=xla resolved nv_pad={DENSE_NV} to "
            f"{eligible!r}, not 'xla' — the env knob is dead"))
    if over != "sort":
        findings.append(_wfind(
            "W002", "coalesce_engine",
            f"nv_pad={cap * 2} resolved to {over!r}, not 'sort': the "
            "flat (src << kbits) | dst key would overflow int32 — the "
            "nv ceiling is not enforced"))
    if ds != "sort":
        findings.append(_wfind(
            "W002", "coalesce_engine",
            f"accum_dtype='ds32' resolved to {ds!r}, not 'sort': the "
            "dense engines have no double-single accumulator"))

    # Raise-guards: legal shape traces; one past FAILS LOUD.
    slab_max = int(laws.get("slab_ne_max", segment.SLAB_NE_MAX))

    def runs(ne_probe, nv_probe=1 << 12):
        jax.eval_shape(
            lambda s, c, w: segment.coalesced_runs(
                s, c, w, nv_pad=nv_probe, engine="sort"),
            _sds((ne_probe,), jnp.int32), _sds((ne_probe,), jnp.int32),
            _sds((ne_probe,), jnp.float32))

    try:
        runs(slab_max)
        facts["slab_at_max"] = "traced"
    except Exception as e:
        findings.append(_wfind(
            "W002", "slab_ne_max",
            f"coalesced_runs at ne_pad == SLAB_NE_MAX ({slab_max}) "
            f"failed to trace: {type(e).__name__}: {e} — the widest "
            "legal slab must stay admissible"))
    try:
        runs(slab_max * 2)
        findings.append(_wfind(
            "W002", "slab_ne_max",
            f"coalesced_runs accepted ne_pad == {slab_max * 2} (one "
            "doubling past SLAB_NE_MAX): the int32 run-id cumsums "
            "would wrap silently — the raise-guard is gone"))
    except ValueError:
        facts["slab_one_past"] = "raised"

    flat_max = int(laws.get("flat_nv_max", seg_coalesce.FLAT_NV_MAX))

    def xla_probe(nv_probe):
        jax.eval_shape(
            lambda s, d, w: seg_coalesce.seg_coalesce_xla(
                s, d, w, nv_pad=nv_probe),
            _sds((1 << 12,), jnp.int32), _sds((1 << 12,), jnp.int32),
            _sds((1 << 12,), jnp.float32))

    try:
        xla_probe(flat_max)
        facts["flat_at_max"] = "traced"
    except Exception as e:
        findings.append(_wfind(
            "W002", "flat_nv_max",
            f"seg_coalesce_xla at nv_pad == FLAT_NV_MAX ({flat_max}) "
            f"failed to trace: {type(e).__name__}: {e}"))
    try:
        xla_probe(flat_max * 2)
        findings.append(_wfind(
            "W002", "flat_nv_max",
            f"seg_coalesce_xla accepted nv_pad == {flat_max * 2}: the "
            "flat (src << kbits) | dst key wraps int32 — the "
            "raise-guard is gone"))
    except ValueError:
        facts["flat_one_past"] = "raised"

    # ds32 cutover: exactly at DS_MIN_TOTAL_WEIGHT, via either gate
    # (weight mass or addend count).
    ds_min = float(laws.get("ds32_min", DS_MIN_TOTAL_WEIGHT))
    below = _accum_name(np.float32, ds_min - 1.0, 0)
    at = _accum_name(np.float32, ds_min, 0)
    by_n = _accum_name(np.float32, 0.0, int(ds_min))
    facts["accum"] = {"below": below, "at": at, "by_addends": by_n}
    if below != "float32" or at != "ds32" or by_n != "ds32":
        findings.append(_wfind(
            "W002", "ds32_cutover",
            f"_accum_name at the DS_MIN_TOTAL_WEIGHT boundary chose "
            f"(below={below!r}, at={at!r}, by_addends={by_n!r}); "
            "expected ('float32', 'ds32', 'ds32') — the threshold-"
            "safety cutover moved"))

    return findings, facts


# ---------------------------------------------------------------------------
# Manifest.


def load_budget(path: str) -> dict:
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    if data.get("version") != BUDGET_VERSION:
        raise ValueError(f"width budget {path!r}: unsupported "
                         f"version {data.get('version')!r}")
    return data


def write_budget(path: str, doc: dict) -> None:
    out = dict(doc)
    out["version"] = BUDGET_VERSION
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(out, fh, indent=2, sort_keys=True)
        fh.write("\n")


def code_laws() -> dict:
    """The laws as the CODE declares them — what the manifest must
    match (W003 cross-check) and what --write-budget regenerates."""
    from cuvite_tpu.kernels.seg_coalesce import FLAT_NV_MAX, _env_max_nv
    from cuvite_tpu.louvain.driver import DS_MIN_TOTAL_WEIGHT
    from cuvite_tpu.ops.segment import SLAB_NE_MAX

    return {
        "index_bits": 32,
        "pack_bits": 31,
        "slab_ne_max": SLAB_NE_MAX,
        "flat_nv_max": FLAT_NV_MAX,
        "coalesce_max_nv": _env_max_nv(),
        "ds32_min": DS_MIN_TOTAL_WEIGHT,
    }


def manifest_crosscheck(manifest: dict) -> list:
    """W003: the checked-in manifest must agree with the code constants
    and the registry's declared max workload — a drifted manifest
    certifies shapes nobody ships."""
    from cuvite_tpu.workloads import registry

    out = []
    laws = manifest.get("laws", {})
    for key, want in sorted(code_laws().items()):
        got = laws.get(key)
        if got != want:
            out.append(_wfind(
                "W003", "manifest",
                f"tools/width_budget.json law '{key}' is {got!r} but "
                f"the code declares {want!r}: the manifest drifted — "
                "regenerate with tools/width_audit.py --write-budget "
                "and review the diff"))
    declared = manifest.get("max_workload", {})
    actual = registry.max_workload()
    if declared != actual:
        out.append(_wfind(
            "W003", "manifest",
            f"manifest max_workload {declared} != registry "
            f"max_workload() {actual}: the width envelope the static "
            "tier certifies against moved without the manifest"))
    if actual != MAX_WORKLOAD:
        out.append(_wfind(
            "W003", "manifest",
            f"registry.max_workload() {actual} != widthcheck."
            f"MAX_WORKLOAD {MAX_WORKLOAD}: the static and dynamic "
            "tiers certify DIFFERENT envelopes"))
    return out


# ---------------------------------------------------------------------------
# The audit.


def run_width_audit(entry_names=None, workloads=None,
                    budget_path: str | None = None,
                    probes: bool = True):
    """(findings, reports) over the certification workloads.

    ``reports``: {workload: {entry: {"sorts", "w001", "nv_pad",
    "ne_shard"}}} plus ``"probes"`` (boundary facts) and ``"spy"``
    (the live-buffer delta).  Results are NEVER cached."""
    import jax

    if budget_path is None:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        budget_path = os.path.join(root, DEFAULT_BUDGET_REL)
    findings: list = []
    reports: dict = {}
    try:
        manifest = load_budget(budget_path)
    except (OSError, ValueError) as e:
        manifest = None
        findings.append(_wfind(
            "W003", "manifest",
            f"width budget unreadable ({e}): the index-width law "
            "inventory is the closed artifact — restore "
            "tools/width_budget.json or regenerate with "
            "tools/width_audit.py --write-budget"))
    if manifest is not None:
        findings.extend(manifest_crosscheck(manifest))
    laws = (manifest or {}).get("laws") or code_laws()
    index_bits = int(laws.get("index_bits", 32))
    pack_bits = int(laws.get("pack_bits", 31))

    names = list(ENTRIES) if entry_names is None else list(entry_names)
    wl = audit_workloads()
    if workloads is not None:
        wl = {k: v for k, v in wl.items() if k in set(workloads)}

    # Warm up every selected entry at a tiny class first so lazily
    # created import-time buffers never pollute the spy's baseline.
    for name in names:
        tracer, _ = ENTRIES[name]
        try:
            tracer(1 << 8, 1 << 10)
        except Exception:
            pass  # the real run reports it as W003
    gc.collect()
    baseline = live_device_bytes()

    for wname, shapes in sorted(wl.items()):
        nv, ne = shapes["nv_pad"], shapes["ne_shard"]
        per: dict = {}
        for name in names:
            tracer, slab_sorts = ENTRIES[name]
            try:
                jaxpr = tracer(nv, ne)
            except Exception as e:  # fail CLOSED: a crashing entry is
                findings.append(_wfind(  # a finding, not a skipped check
                    "W003", name,
                    f"entry '{name}' failed to trace at workload "
                    f"'{wname}' (nv_pad={nv}, ne_shard={ne}): "
                    f"{type(e).__name__}: {e}"))
                continue
            w001 = index_width_findings(jaxpr, name, index_bits)
            findings.extend(w001)
            sorts = sort_facts(jaxpr)
            del jaxpr
            if slab_sorts and not _pack_eligible(nv, pack_bits) \
                    and any(nk == 1 and dt == "int32" and nd == 1
                            for nk, dt, nd in sorts):
                findings.append(_wfind(
                    "W002", name,
                    f"'{name}' at workload '{wname}' (nv_pad={nv}: "
                    f"kbits+sbits > {pack_bits}) still traced an int32 "
                    f"single-key sort ({sorts}): the lexicographic "
                    "fallback was NOT selected on the first ineligible "
                    "shape — packed keys are wrapping the sign bit"))
            per[name] = {"nv_pad": nv, "ne_shard": ne,
                         "sorts": sorts, "w001": len(w001)}
        reports[wname] = per

    if probes:
        probe_findings, probe_facts = boundary_probes(laws)
        findings.extend(probe_findings)
        reports["probes"] = probe_facts

    gc.collect()
    delta = live_device_bytes() - baseline
    reports["spy"] = {"baseline_bytes": baseline, "delta_bytes": delta}
    if delta != 0:
        findings.append(_wfind(
            "W003", "alloc_spy",
            f"the trace sweep allocated {delta} live device bytes; the "
            "scale-28 certification is only honest at ZERO — some "
            "entry concretized (device_put / block_until_ready / eager "
            "constant) instead of staging abstractly"))
    return findings, reports
