"""Tier 2 — cross-module jit-reachability dataflow (R017/R018).

The per-file engine deliberately stops at module boundaries: its
``jit_reachable`` closure links calls by bare name within one file, and
ANALYSIS.md lists "a host-sync hidden behind a cross-module call from a
jitted function" as the known false negative.  This module closes that
hole with a *project-wide* pass:

  1. every linted file is reduced to a :func:`summarize` dict — imports,
     functions, their resolved callee names, jit/vmap/shard_map entry
     flags, and the host-sync / device-pull call sites the cross-module
     rules may need to anchor findings on.  Summaries are plain JSON
     (they ride the incremental lint cache, analysis/cache.py), so the
     whole-program pass never needs the ASTs of unchanged files;
  2. :class:`Project` links the summaries into one call graph.  Edges
     are followed only where they can be PROVEN: an import-resolved
     dotted call (``driver._run_phase_loop(...)`` under ``from
     cuvite_tpu.louvain import driver``) crosses modules, a bare name
     links within its module (the same semantics the per-file closure
     uses).  Unresolvable receivers (``self.x()``, call results) fall
     back to the bare-name link — bounded, never global;
  3. jit-reachability propagates from every entry point — ``jax.jit`` /
     ``pjit`` roots (the engine's ``_JIT_NAMES``), plus ``shard_map`` /
     ``vmap`` / ``pmap`` wraps and the factory idiom where the wrapped
     callable flows through a local assignment first
     (``body = functools.partial(_phase_body, ...); jax.jit(body)``,
     the louvain/batched.py shape);
  4. R017 re-runs the host-sync check (R001's call set) against the
     TRANSITIVE closure: a helper calling ``jax.device_get`` two modules
     away from its jitted caller is a high finding, with the reach chain
     spelled out in the message.  R018 re-runs the device-pull check
     (R010's call set) against reachability from the phase-transition
     modules: a pull that R010 cannot see because the helper lives
     outside ``louvain/``/``coarsen/`` is flagged at its true call site.

Findings anchor on real (path, line, snippet) triples, so baselining and
inline ``# graftlint: disable=R017`` suppressions work exactly as they
do for per-file rules.
"""

from __future__ import annotations

import ast
import collections

from cuvite_tpu.analysis.engine import (
    _JIT_NAMES,
    Finding,
    Rule,
    SourceFile,
    dotted,
    register,
)
from cuvite_tpu.analysis.rules import (
    _DEVICE_NAME_PREFIXES,
    _DEVICE_NAME_SUFFIXES,
    _HOST_MATERIALIZE_CALLS,
    _HOST_PULL_CALLS,
    HOST_SYNC_ATTRS,
    HOST_SYNC_CALLS,
    PHASE_TRANSITION_PREFIXES,
)

# Everything that makes the wrapped/decorated callable a traced entry
# point: jit/pjit (the engine's set) plus the batching/SPMD transforms.
JIT_ENTRY_CALLS = set(_JIT_NAMES) | {
    "vmap", "jax.vmap", "pmap", "jax.pmap",
    "shard_map", "jax.experimental.shard_map.shard_map",
}

SUMMARY_VERSION = 7


def module_of(rel: str) -> str:
    """Dotted module name for a repo-relative path ('tools/x.py' ->
    'tools.x'; package __init__ collapses to the package)."""
    mod = rel[:-3] if rel.endswith(".py") else rel
    mod = mod.replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


_PARTIAL_NAMES = {"functools.partial", "partial"}
_ENTRY_LAST_PARTS = {"shard_map", "vmap", "pmap"}


def _is_entry_call_name(name: str | None) -> bool:
    return bool(name) and (name in JIT_ENTRY_CALLS
                           or name.split(".")[-1] in _ENTRY_LAST_PARTS)


def _forwarded_names(expr: ast.AST) -> set:
    """Names ``expr`` can FORWARD as the wrapped callable: a bare name,
    a ternary of forwardable names, the callable slot of a
    ``functools.partial``, or the first argument of a nested entry
    transform (``jax.jit(shard_map(body, ...))``).  Deliberately NOT
    'every Name in the expression' — treating call arguments or mesh
    objects as callables is how a reachability pass drowns in false
    entries."""
    out: set = set()
    if isinstance(expr, ast.Name):
        out.add(expr.id)
    elif isinstance(expr, ast.IfExp):
        out |= _forwarded_names(expr.body) | _forwarded_names(expr.orelse)
    elif isinstance(expr, ast.Call):
        fname = dotted(expr.func)
        if fname in _PARTIAL_NAMES and expr.args:
            out |= _forwarded_names(expr.args[0])
        elif _is_entry_call_name(fname) and expr.args:
            out |= _forwarded_names(expr.args[0])
    return out


def _entry_seed_names(sf: SourceFile) -> set:
    """Local function names wrapped at a call site by a jit/vmap/
    shard_map entry call, including flow through local assignments in
    the same scope (the ``body = functools.partial(_phase_body, ...);
    jax.jit(shard_map(body, ...))`` factory idiom in louvain/batched).
    Scope-aware: an assignment in one function never feeds a wrap in
    another."""
    assign_map: dict = {}  # (scope id, name) -> forwardable names
    for node in sf.walk():
        if not isinstance(node, ast.Assign):
            continue
        fwd = _forwarded_names(node.value)
        if not fwd:
            continue
        scope = sf.enclosing_function(node)
        for t in node.targets:
            if isinstance(t, ast.Name):
                assign_map.setdefault((id(scope), t.id), set()).update(fwd)
    seeds: set = set()
    for node in sf.walk():
        if not isinstance(node, ast.Call) \
                or not _is_entry_call_name(dotted(node.func)) \
                or not node.args:
            continue
        scope = sf.enclosing_function(node)
        work = _forwarded_names(node.args[0])
        for _ in range(4):  # bounded assignment-chain expansion
            nxt = set()
            for n in work:
                nxt |= assign_map.get((id(scope), n), set())
                nxt |= assign_map.get((id(None), n), set())
            if nxt <= work:
                break
            work |= nxt
        seeds |= work
    return seeds


# The tier-2 host-sync call set: R001's minus the bare float()/int()/
# bool() conversions.  In-module, the engine KNOWS a function is traced,
# so concretizing casts are real findings; across modules most reached
# helpers also run at trace time on static values (shape math, accum
# tags), where int(nv_pad) is idiomatic — keeping the casts would bury
# the unambiguous pulls under hundreds of false positives.  The
# unambiguous set: explicit device pulls and array materializations.
TRANSITIVE_SYNC_CALLS = HOST_SYNC_CALLS - {"float", "int", "bool"}
TRANSITIVE_SYNC_ATTRS = HOST_SYNC_ATTRS


def _classify_call(sf: SourceFile, node: ast.Call):
    """(sync_label, pull_label) for one call node — the R001 host-sync
    and R010 device-pull classifications, shared (minus the trace-time
    casts, see TRANSITIVE_SYNC_CALLS) so tier 2 cannot drift from the
    per-file rules."""
    name = dotted(node.func)
    sync = None
    if name in TRANSITIVE_SYNC_CALLS:
        sync = f"{name}()"
    elif isinstance(node.func, ast.Attribute) \
            and node.func.attr in TRANSITIVE_SYNC_ATTRS and not node.args:
        sync = f".{node.func.attr}()"
    pull = None
    if name in _HOST_PULL_CALLS:
        pull = f"{name}()"
    elif name in _HOST_MATERIALIZE_CALLS and node.args:
        arg = node.args[0]
        if isinstance(arg, ast.Name) and (
                arg.id.endswith(_DEVICE_NAME_SUFFIXES)
                or arg.id.startswith(_DEVICE_NAME_PREFIXES)):
            pull = f"{name}({arg.id})"
    return sync, pull


def summarize(sf: SourceFile) -> dict:
    """The JSON-serializable cross-module facts of one file (see module
    docstring).  Everything tier 2 reads comes from here — the ASTs of
    cache-hit files are never rebuilt."""
    imports: dict = {}       # local alias -> full module name
    from_imports: dict = {}  # local name -> [module, symbol]
    mod = module_of(sf.rel)
    pkg_parts = mod.split(".")
    if not sf.rel.endswith("__init__.py"):
        pkg_parts = pkg_parts[:-1]
    for node in sf.walk():
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    imports[a.asname] = a.name
                else:
                    # `import a.b.c` binds the TOP package; the dotted
                    # call path supplies the rest (a.b.c.f resolves by
                    # appending the middle parts to the head binding).
                    head = a.name.split(".")[0]
                    imports[head] = head
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                base = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                src = ".".join(base + ([node.module] if node.module else []))
            else:
                src = node.module or ""
            for a in node.names:
                if a.name == "*":
                    continue
                from_imports[a.asname or a.name] = [src, a.name]

    seeds = _entry_seed_names(sf)
    # Wrapped names that are NOT local functions (``jax.jit(step)``
    # where step was imported): recorded raw, resolved to their home
    # module at project-link time.
    entry_wraps = sorted(s for s in seeds if s not in sf.func_by_name)
    entry_decorators = JIT_ENTRY_CALLS
    funcs = []
    # Group call facts by enclosing FunctionInfo in ONE walk (the
    # per-function re-walk is quadratic on big files).
    per_func: dict = collections.defaultdict(
        lambda: {"calls": set(), "sync": [], "pull": []})
    for node in sf.walk():
        if not isinstance(node, ast.Call):
            continue
        info = sf.enclosing_function(node)
        if info is None:
            continue
        facts = per_func[id(info)]
        name = dotted(node.func)
        if name:
            facts["calls"].add(name)
        elif isinstance(node.func, ast.Attribute):
            facts["calls"].add(node.func.attr)
        sync, pull = _classify_call(sf, node)
        line = getattr(node, "lineno", 1)
        if sync:
            facts["sync"].append(
                {"label": sync, "line": line, "snippet": sf.line(line)})
        if pull:
            facts["pull"].append(
                {"label": pull, "line": line, "snippet": sf.line(line)})
    for info in sf.functions:
        is_entry = info.is_jit or info.name in seeds or any(
            (dotted(d) in entry_decorators)
            or (isinstance(d, ast.Call) and dotted(d.func) in entry_decorators)
            for d in info.node.decorator_list)
        facts = per_func.get(id(info), {"calls": set(), "sync": [],
                                        "pull": []})
        funcs.append({
            "name": info.name,
            "line": getattr(info.node, "lineno", 1),
            "entry": bool(is_entry),
            "local_jit_reachable": bool(info.jit_reachable),
            "calls": sorted(facts["calls"]),
            "sync_sites": facts["sync"],
            "pull_sites": facts["pull"],
        })
    # Tier-4/5 static facts ride the same summary (and therefore the
    # same incremental-cache entry): the R020 acquisition graph and the
    # R023-R025 mesh facts are rebuilt from cached summaries exactly
    # like R017/R018 are from the dataflow ones.  Lazy import: both
    # modules subclass ProjectRule from THIS module.
    from cuvite_tpu.analysis import lockorder, meshspec, widthcheck

    return {
        "version": SUMMARY_VERSION,
        "rel": sf.rel,
        "module": mod,
        "imports": imports,
        "from_imports": from_imports,
        "entry_wraps": entry_wraps,
        "functions": funcs,
        "locks": lockorder.lock_summary(sf),
        "mesh": meshspec.mesh_summary(sf),
        "width": widthcheck.width_summary(sf),
        "suppress": {str(ln): sorted(ids)
                     for ln, ids in sf._line_suppress.items()},
        "file_suppress": sorted(sf._file_suppress),
    }


class Project:
    """The linked whole-program view over a set of file summaries."""

    def __init__(self, summaries):
        self.summaries = [s for s in summaries
                          if s and s.get("version") == SUMMARY_VERSION]
        self.by_module: dict = {}
        for s in self.summaries:
            self.by_module[s["module"]] = s
        # (module, func name) -> list of function dicts (same-named defs
        # collapse, matching the per-file closure's name semantics).
        self.funcs: dict = collections.defaultdict(list)
        for s in self.summaries:
            for fn in s["functions"]:
                self.funcs[(s["module"], fn["name"])].append(fn)
        self._edges_cache: dict = {}

    # -- linking -------------------------------------------------------

    def _resolve(self, summary: dict, callee: str):
        """One raw callee name -> (module, funcname) or None.  Dotted
        names resolve through the module's imports (longest module
        prefix wins); anything unresolved degrades to a bare-name link
        within the module — exactly the per-file closure's reach."""
        parts = callee.split(".")
        if len(parts) > 1:
            head, last = parts[0], parts[-1]
            tgt = None
            if head in summary["imports"]:
                base = summary["imports"][head]
                mid = parts[1:-1]
                tgt = ".".join([base] + mid)
            elif head in summary["from_imports"]:
                m, sym = summary["from_imports"][head]
                tgt = ".".join([m, sym] + parts[1:-1])
            if tgt is not None and tgt in self.by_module \
                    and (tgt, last) in self.funcs:
                return (tgt, last)
            return (summary["module"], last)
        if callee in summary["from_imports"]:
            m, sym = summary["from_imports"][callee]
            # `from pkg import mod` binds a submodule, not a symbol.
            if ".".join([m, sym]) in self.by_module:
                return None
            if (m, sym) in self.funcs:
                return (m, sym)
            # Symbol re-exported through a package __init__: best-effort
            # one-hop follow of ITS from-imports.
            pkg = self.by_module.get(m)
            if pkg and sym in pkg["from_imports"]:
                m2, sym2 = pkg["from_imports"][sym]
                if (m2, sym2) in self.funcs:
                    return (m2, sym2)
            return None
        return (summary["module"], callee)

    def _edges_of(self, module: str, fn: dict) -> list:
        key = (module, fn["name"], fn["line"])
        hit = self._edges_cache.get(key)
        if hit is not None:
            return hit
        summary = self.by_module[module]
        out = []
        for callee in fn["calls"]:
            tgt = self._resolve(summary, callee)
            if tgt is not None and tgt in self.funcs:
                out.append(tgt)
        self._edges_cache[key] = out
        return out

    def _reach(self, seed_keys) -> dict:
        """BFS over the call graph; returns {(module, name): pred-key}
        (seeds map to None) for chain reconstruction."""
        pred: dict = {}
        queue = collections.deque()
        for k in seed_keys:
            if k in self.funcs and k not in pred:
                pred[k] = None
                queue.append(k)
        while queue:
            cur = queue.popleft()
            for fn in self.funcs[cur]:
                for tgt in self._edges_of(cur[0], fn):
                    if tgt not in pred:
                        pred[tgt] = cur
                        queue.append(tgt)
        return pred

    def chain(self, pred: dict, key) -> str:
        parts = []
        seen = set()
        while key is not None and key not in seen:
            seen.add(key)
            mod, name = key
            rel = self.by_module[mod]["rel"]
            parts.append(f"{rel}::{name}")
            key = pred.get(key)
        return " <- ".join(parts)

    # -- rule-facing helpers -------------------------------------------

    def jit_reach(self) -> dict:
        seeds = [(s["module"], fn["name"]) for s in self.summaries
                 for fn in s["functions"] if fn["entry"]]
        # Imported callables wrapped at a call site (jax.jit(step) where
        # step came from another module) seed their HOME definition.
        for s in self.summaries:
            for name in s.get("entry_wraps", ()):
                tgt = self._resolve(s, name)
                if tgt is not None and tgt in self.funcs:
                    seeds.append(tgt)
        return self._reach(seeds)

    def phase_transition_reach(self) -> dict:
        seeds = [(s["module"], fn["name"]) for s in self.summaries
                 if s["rel"].startswith(PHASE_TRANSITION_PREFIXES)
                 for fn in s["functions"]]
        return self._reach(seeds)

    def suppressed(self, summary: dict, line: int, rule_id: str) -> bool:
        fs = set(summary.get("file_suppress", ()))
        if rule_id in fs or "all" in fs:
            return True
        ids = set(summary.get("suppress", {}).get(str(line), ()))
        return rule_id in ids or "all" in ids


class ProjectRule(Rule):
    """A rule that needs the whole-program view.  ``check`` (per-file)
    is a no-op; the engine's project pass calls ``check_project``."""

    def check(self, sf):
        return ()

    def check_project(self, project: Project):
        raise NotImplementedError

    def project_finding(self, summary: dict, site: dict,
                        message: str) -> Finding:
        return Finding(rule=self.id, severity=self.severity,
                       path=summary["rel"], line=site["line"],
                       message=message, snippet=site["snippet"])


@register
class TransitiveHostSync(ProjectRule):
    id = "R017"
    severity = "high"
    title = "host-sync call transitively reachable from a jit/vmap/" \
            "shard_map entry point (cross-module)"

    def check_project(self, project: Project):
        pred = project.jit_reach()
        for summary in project.summaries:
            mod = summary["module"]
            for fn in summary["functions"]:
                key = (mod, fn["name"])
                if key not in pred:
                    continue
                if fn["local_jit_reachable"]:
                    continue  # R001's per-file closure already covers it
                chain = project.chain(pred, key)
                for site in fn["sync_sites"]:
                    yield self.project_finding(
                        summary, site,
                        f"{site['label']} in '{fn['name']}' is "
                        f"transitively reachable from a traced entry "
                        f"point ({chain}): a blocking device->host sync "
                        "(or trace-time concretization) the per-file "
                        "R001 closure cannot see across the module "
                        "boundary")


@register
class TransitiveDevicePull(ProjectRule):
    id = "R018"
    severity = "high"
    title = "device->host pull in a helper reached from phase-" \
            "transition code (cross-module)"

    def check_project(self, project: Project):
        pred = project.phase_transition_reach()
        for summary in project.summaries:
            if summary["rel"].startswith(PHASE_TRANSITION_PREFIXES):
                continue  # R010 owns the in-scope modules
            mod = summary["module"]
            for fn in summary["functions"]:
                key = (mod, fn["name"])
                if key not in pred:
                    continue
                chain = project.chain(pred, key)
                for site in fn["pull_sites"]:
                    yield self.project_finding(
                        summary, site,
                        f"{site['label']} in '{fn['name']}' is reached "
                        f"from phase-transition code ({chain}): the "
                        "O(E)/O(V) host materialization R010 polices "
                        "has moved into a helper module where the "
                        "per-file rule cannot see it; keep the slab in "
                        "HBM or justify with an inline disable")


def run_project(summaries, rules=None) -> list:
    """All project-tier findings over a summary set, suppression-
    filtered.  ``rules`` (when given) selects which ProjectRules run —
    the same contract as run_source's ``rules``."""
    from cuvite_tpu.analysis.engine import all_rules

    project = Project(summaries)
    selected = [r for r in (all_rules() if rules is None else rules)
                if isinstance(r, ProjectRule)]
    out = []
    seen = set()
    for rule in selected:
        for f in rule.check_project(project):
            summary = project.by_module.get(module_of(f.path))
            if summary is not None \
                    and project.suppressed(summary, f.line, f.rule):
                continue
            # Same-named defs collapse in the call graph, so one site
            # can surface once per homonym — dedupe on the anchor.
            key = (f.path, f.line, f.rule)
            if key in seen:
                continue
            seen.add(key)
            out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


def run_project_sources(sources: dict, rules=None) -> list:
    """Test-facing: lint a {rel: source text} dict as one project —
    per-file findings plus the cross-module tier, exactly what
    run_paths produces for the same tree on disk."""
    from cuvite_tpu.analysis.engine import run_source

    findings = []
    summaries = []
    for rel, text in sorted(sources.items()):
        findings.extend(run_source(text, path=rel, rules=rules, rel=rel))
        summaries.append(summarize(SourceFile(text, path=rel, rel=rel)))
    findings.extend(run_project(summaries, rules=rules))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
