"""Tier 5 (static half) — SPMD mesh/collective analysis (R023-R025).

ROADMAP item 5 (the hierarchical two-level ICI/DCN exchange) is a
structural SPMD change: it adds a second mesh axis, re-routes
collectives across it, and re-partitions the replicated community
tables.  The reference's synchronized-collective design (Bhowmick et
al., arXiv:1702.04645) rests on every rank issuing the identical
collective sequence over axes its mesh actually has — and nothing in
tiers 1-4 machine-checks that.  This module closes the static half
(analysis/meshcheck.py runs the dynamic half, M001-M003):

**Facts** (:func:`mesh_summary`, riding the tier-2 summary and the
incremental lint cache exactly like the lock summaries): per file —
module-level string constants (axis names live in constants:
``VERTEX_AXIS = "v"``), ``Mesh(...)`` constructions with their
resolvable axis-name tuples, ``shard_map`` wrap sites (call-site and
partial-decorator spellings) with the wrapped callable names and the
axis tokens their ``P(...)`` specs mention, SPMD collective call sites
(``psum``/``all_to_all``/``all_gather``/``ppermute``/...) with their
axis argument classified (literal / module constant / enclosing-
function parameter), and O(nv_total) materialization sites with their
``# graftlint: replicated-ok=<reason>`` annotations.

**R023 — axis-name drift** (project tier).  A collective's axis name,
resolved cross-module (parameters chase their call-site bindings
through the project call graph, depth-bounded), must be (a) an axis of
*some* constructed mesh, and (b) admitted by at least one of the
shard_map wraps whose body reaches the collective.  Violation (a) is
the typo/rename class; violation (b) is the exact bug a two-level
ICI/DCN split introduces — a helper still issuing ``psum(x, "v")``
after the mesh became ``("ici", "dcn")``.

**R024 — whole-program collective-order divergence** (project tier).
R004 lifted off the single file: an SPMD collective under a
data-dependent or fallible branch (the same divergence classifier R004
uses, plus ``try``) in ANY function reachable from a shard_map body,
with the reach chain in the message.  R004 keeps its per-file cases —
the two rules partition by collective set (host-side multihost
wrappers stay R004's; device collectives are R024's).

**R025 — replication audit** (project tier).  A device buffer whose
symbolic size scales with ``nv_total`` (``jnp.zeros((nv_total,))``,
``segment_sum(..., num_segments=nv_total)``, an ``all_gather`` of a
sharded table) materialized inside a function reachable from a
shard_map body is O(total vertices) **per chip** — round-8 measured
exactly these tables as the wall the sparse cutover exists for.  Every
such site must carry ``# graftlint: replicated-ok=<reason>`` on its
line, so the replicated tables form a closed, justified inventory
(:func:`replicated_inventory`) — the starting point ROADMAP item 5
needs.  Per-shard ``nv_pad``-sized buffers are sharded by construction
and out of scope here; the dynamic M003 scaling check covers them.
"""

from __future__ import annotations

import ast
import re

from cuvite_tpu.analysis.engine import Finding, SourceFile, dotted, register
from cuvite_tpu.analysis.rules import (
    COLLECTIVE_NAMES,
    _condition_is_divergent,
)

MESH_SUMMARY_VERSION = 2

# Device/SPMD collective primitives (matched on the dotted name's last
# part).  Host-side multihost wrappers (COLLECTIVE_NAMES) are R004's
# domain and excluded here, so the two rules never double-report.
SPMD_COLLECTIVES = {
    "psum", "pmax", "pmin", "pmean", "all_to_all", "all_gather",
    "ppermute", "pshuffle", "psum_scatter", "axis_index",
}
# axis_index is not a communication op; it anchors axis-name facts but
# never a divergence finding.
_ORDERING_COLLECTIVES = SPMD_COLLECTIVES - {"axis_index"}

# Size symbols whose presence in a shape/num_segments expression marks
# an O(nv_total)-per-chip materialization (R025).  nv_pad/nv_local are
# per-shard sizes — sharded by construction, dynamic M003's job.
SIZE_SYMBOLS = ("nv_total",)

_ALLOC_CALLS = {
    "zeros", "ones", "full", "empty", "arange", "broadcast_to",
}
_SEGMENT_PREFIX = "segment_"

_REPL_OK_RE = re.compile(r"#\s*graftlint:\s*replicated-ok\s*=\s*(.+?)\s*$")

# Optional machine-readable scope prefix on a replicated-ok reason:
# ``scope=ici; <prose>`` declares the buffer's replication extent.
# ``ici`` = the table is materialized only inside the fast submesh (a
# flat mesh is the degenerate single-ICI-group case — the replicated/
# sort exchanges are rejected on hybrid meshes, so their gather axis
# never spans more than one ICI group); ``scalar`` = not vertex-scaled
# (O(nshards) bytes).  A reason with no prefix reads as scope=global —
# the two-level inventory contract is that NO site keeps that scope.
_SCOPE_RE = re.compile(r"^scope=([A-Za-z0-9_]+)\s*;\s*")


def _last(name: str | None) -> str:
    return name.split(".")[-1] if name else ""


def _enclosing_with_param(sf: SourceFile, node: ast.AST, name: str):
    """The nearest enclosing function that binds ``name`` as a
    parameter, or None — closures see outer-function parameters, so an
    axis Name inside a nested shard_map body resolves to the FACTORY's
    parameter (the make_sharded_step shape)."""
    for anc in sf.ancestors(node):
        info = sf.func_of_node.get(anc)
        if info is not None and name in info.params:
            return info
    return None


def _module_consts(sf: SourceFile) -> dict:
    """Module-level ``NAME = "str"`` constants (axis names live here:
    VERTEX_AXIS/BATCH_AXIS)."""
    out: dict = {}
    for node in sf.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            out[node.targets[0].id] = node.value.value
    return out


def _axis_token(sf: SourceFile, consts: dict, node: ast.AST) -> list:
    """Classify one axis-name expression into a JSON token:
    ``["lit", v]`` / ``["name", n]`` (module const or import, resolved
    at project tier) / ``["param", fn, p]`` / ``["unknown", src]``."""
    if isinstance(node, ast.Constant):
        if isinstance(node.value, str):
            return ["lit", node.value]
        return ["unknown", repr(node.value)]
    if isinstance(node, ast.Name):
        if node.id in consts:
            return ["lit", consts[node.id]]
        info = _enclosing_with_param(sf, node, node.id)
        if info is not None:
            return ["param", info.name, node.id]
        return ["name", node.id]
    try:
        return ["unknown", ast.unparse(node)]
    except Exception:
        return ["unknown", "<expr>"]


# Collectives whose axis name is the FIRST positional argument
# (everything else takes (operand, axis_name, ...)).
_AXIS_FIRST_ARG = {"axis_index"}


def _collective_axis(sf, consts, node: ast.Call) -> list:
    for kw in node.keywords:
        if kw.arg in ("axis_name", "axes", "axis"):
            return _axis_token(sf, consts, kw.value)
    if _last(dotted(node.func)) in _AXIS_FIRST_ARG and node.args:
        return _axis_token(sf, consts, node.args[0])
    if len(node.args) >= 2:
        return _axis_token(sf, consts, node.args[1])
    return ["unknown", "<none>"]


def _divergence_reason(sf: SourceFile, node: ast.AST) -> str | None:
    """Why the collective at ``node`` may be issued by some shards/hosts
    and not others: the R004 classifier applied to every enclosing
    ``if``/``while`` up to the function boundary, plus ``try``."""
    info = sf.enclosing_function(node)
    boundary = info.node if info is not None else None
    child = node
    for anc in sf.ancestors(node):
        if anc is boundary:
            return None
        if isinstance(anc, ast.Try):
            return "inside a try block (an exception skips the " \
                   "remaining collectives on that shard only)"
        if isinstance(anc, (ast.If, ast.While)) and child is not anc.test:
            why = _condition_is_divergent(anc.test)
            if why:
                return why
        child = anc
    return None


def _spec_axis_tokens(sf, consts, expr: ast.AST) -> list:
    """Axis tokens mentioned by an in_specs/out_specs expression: every
    argument of every ``P(...)`` / ``PartitionSpec(...)`` call in it."""
    toks = []
    for node in ast.walk(expr):
        if isinstance(node, ast.Call) \
                and _last(dotted(node.func)) in ("P", "PartitionSpec"):
            for a in node.args:
                if isinstance(a, ast.Constant) and a.value is None:
                    continue
                toks.append(_axis_token(sf, consts, a))
    return toks


def _forwarded(expr: ast.AST) -> set:
    """Callable names ``expr`` can forward (bare name / partial /
    ternary) — the callgraph helper, reused so wrap-target semantics
    cannot drift between tiers."""
    from cuvite_tpu.analysis.callgraph import _forwarded_names

    return _forwarded_names(expr)


def _replicated_ok_lines(sf: SourceFile) -> dict:
    """{lineno: reason} for every ``# graftlint: replicated-ok=`` pragma
    (real comment tokens, same discipline as the disable pragmas)."""
    out: dict = {}
    for lineno, comment in sf._iter_comments():
        if "replicated-ok" not in comment:
            continue
        m = _REPL_OK_RE.search(comment)
        if m:
            out[lineno] = m.group(1)
    return out


def mesh_summary(sf: SourceFile) -> dict:
    """The JSON-serializable SPMD facts of one file (see module
    docstring); rides the tier-2 summary under the ``"mesh"`` key."""
    consts = _module_consts(sf)
    repl_ok = _replicated_ok_lines(sf)
    meshes: list = []
    wraps: list = []
    collectives: list = []
    allocs: list = []
    binds: list = []
    params: dict = {}
    for info in sf.functions:
        params.setdefault(info.name, list(info.params))

    # Local assignments forwarding callables (body = partial(f, ...)),
    # scope-keyed like callgraph._entry_seed_names.
    assign_map: dict = {}
    for node in sf.walk():
        if isinstance(node, ast.Assign):
            fwd = _forwarded(node.value)
            if fwd:
                scope = sf.enclosing_function(node)
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        assign_map.setdefault(
                            (id(scope), t.id), set()).update(fwd)

    def expand_targets(node, names: set) -> list:
        scope = sf.enclosing_function(node)
        work = set(names)
        for _ in range(4):
            nxt = set()
            for n in work:
                nxt |= assign_map.get((id(scope), n), set())
                nxt |= assign_map.get((id(None), n), set())
            if nxt <= work:
                break
            work |= nxt
        return sorted(work)

    def record_wrap(node, fn_name, targets, spec_axes):
        wraps.append({
            "fn": fn_name,
            "line": getattr(node, "lineno", 1),
            "snippet": sf.line(getattr(node, "lineno", 1)),
            "targets": targets,
            "axes": spec_axes,
        })

    def size_symbol_of(expr: ast.AST) -> str | None:
        for n in ast.walk(expr):
            if isinstance(n, ast.Name) and n.id in SIZE_SYMBOLS:
                return n.id
            if isinstance(n, ast.Attribute) and n.attr in SIZE_SYMBOLS:
                return n.attr
        return None

    for node in sf.walk():
        # shard_map decorator spellings on defs:
        #   @shard_map(mesh=..., in_specs=...)   (the comm.mesh wrapper)
        #   @functools.partial(shard_map, mesh=..., in_specs=...)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if not isinstance(dec, ast.Call):
                    continue
                fname = dotted(dec.func)
                target_call = dec
                if _last(fname) == "partial" and dec.args \
                        and _last(dotted(dec.args[0])) == "shard_map":
                    pass
                elif _last(fname) == "shard_map":
                    pass
                else:
                    continue
                spec_axes = []
                for kw in target_call.keywords:
                    if kw.arg in ("in_specs", "out_specs"):
                        spec_axes.extend(
                            _spec_axis_tokens(sf, consts, kw.value))
                info = sf.enclosing_function(node)
                record_wrap(dec, info.name if info else "",
                            [node.name], spec_axes)
            continue
        if not isinstance(node, ast.Call):
            continue
        fname = dotted(node.func)
        last = _last(fname)
        info = sf.enclosing_function(node)
        fn_name = info.name if info is not None else ""
        line = getattr(node, "lineno", 1)

        # Mesh constructions: Mesh(devices, ('v',)) / jax.make_mesh.
        if last in ("Mesh", "make_mesh") and len(node.args) >= 2:
            axes = [_axis_token(sf, consts, el)
                    for el in (node.args[1].elts
                               if isinstance(node.args[1],
                                             (ast.Tuple, ast.List))
                               else [node.args[1]])]
            meshes.append({"fn": fn_name, "line": line,
                           "snippet": sf.line(line), "axes": axes})

        # shard_map call-site wraps: shard_map(body, mesh=..., ...).
        if last == "shard_map" and node.args:
            spec_axes = []
            for kw in node.keywords:
                if kw.arg in ("in_specs", "out_specs"):
                    spec_axes.extend(_spec_axis_tokens(sf, consts,
                                                       kw.value))
            targets = expand_targets(node, _forwarded(node.args[0]))
            record_wrap(node, fn_name, targets, spec_axes)

        # SPMD collectives with their axis argument.
        if last in SPMD_COLLECTIVES and last not in COLLECTIVE_NAMES:
            collectives.append({
                "fn": fn_name, "call": fname or last, "line": line,
                "snippet": sf.line(line),
                "axis": _collective_axis(sf, consts, node),
                "divergent": (_divergence_reason(sf, node)
                              if last in _ORDERING_COLLECTIVES else None),
            })
            if last == "all_gather":
                # all_gather materializes the gathered axis replicated
                # per chip — an R025 site regardless of symbol names.
                allocs.append({
                    "fn": fn_name, "call": fname or last, "line": line,
                    "snippet": sf.line(line), "size": "all_gather",
                    "replicated_ok": repl_ok.get(line),
                })

        # O(nv_total) materializations (R025).
        sym = None
        if last in _ALLOC_CALLS and node.args:
            # broadcast_to(arr, shape): the size lives in the SECOND
            # positional; everything else takes the shape first.
            shape_arg = node.args[1] \
                if last == "broadcast_to" and len(node.args) >= 2 \
                else node.args[0]
            sym = size_symbol_of(shape_arg)
        if sym is None and last.startswith(_SEGMENT_PREFIX):
            for kw in node.keywords:
                if kw.arg == "num_segments":
                    sym = size_symbol_of(kw.value)
            if sym is None and len(node.args) >= 3:
                # num_segments spelled positionally:
                # segment_sum(data, segment_ids, num_segments).
                sym = size_symbol_of(node.args[2])
        if sym is not None:
            allocs.append({
                "fn": fn_name, "call": fname or last, "line": line,
                "snippet": sf.line(line), "size": sym,
                "replicated_ok": repl_ok.get(line),
            })

        # Axis-relevant call-site bindings, for parameter resolution:
        # keyword args whose name mentions axis, positional string
        # literals, and positional Names that resolve to axis-ish
        # tokens.  Bounded: nothing else is recorded.
        bind_pos: dict = {}
        bind_kw: dict = {}
        for i, a in enumerate(node.args):
            if isinstance(a, ast.Constant) and isinstance(a.value, str):
                bind_pos[str(i)] = ["lit", a.value]
            elif isinstance(a, ast.Name) \
                    and ("axis" in a.id.lower() or a.id in consts):
                bind_pos[str(i)] = _axis_token(sf, consts, a)
        for kw in node.keywords:
            if kw.arg and "axis" in kw.arg.lower():
                bind_kw[kw.arg] = _axis_token(sf, consts, kw.value)
        if (bind_pos or bind_kw) and fname:
            binds.append({"fn": fn_name, "callee": fname,
                          "pos": bind_pos, "kw": bind_kw})

    return {
        "version": MESH_SUMMARY_VERSION,
        "consts": consts,
        "params": params,
        "meshes": meshes,
        "shard_maps": wraps,
        "collectives": collectives,
        "allocs": allocs,
        "binds": binds,
    }


# ---------------------------------------------------------------------------
# Project-tier linking.


class MeshProject:
    """Axis-resolution view over a linked summary set (wraps a
    callgraph.Project; built once per run_project pass)."""

    MAX_DEPTH = 5

    def __init__(self, project):
        self.project = project
        self.mesh_of: dict = {}
        for s in project.summaries:
            self.mesh_of[s["module"]] = s.get("mesh") or {
                "consts": {}, "params": {}, "meshes": [],
                "shard_maps": [], "collectives": [], "allocs": [],
                "binds": [],
            }
        # (module, funcname) -> [(caller module, bind dict)]
        self.call_binds: dict = {}
        for s in project.summaries:
            mesh = self.mesh_of[s["module"]]
            for b in mesh["binds"]:
                tgt = project._resolve(s, b["callee"])
                if tgt is None or tgt not in project.funcs:
                    continue
                self.call_binds.setdefault(tgt, []).append(
                    (s["module"], b))

    # -- axis-token resolution -----------------------------------------

    def resolve_token(self, module: str, token, depth: int = None,
                      seen=None) -> set:
        """Literal axis strings a token can denote ({} = unresolved)."""
        if depth is None:
            depth = self.MAX_DEPTH
        if not token or depth <= 0:
            return set()
        kind = token[0]
        if kind == "lit":
            return {token[1]}
        if kind == "name":
            # Module const (already folded at summarize time) or an
            # imported constant: follow the from-import to its home
            # module's consts.
            summary = self.project.by_module.get(module)
            if summary is None:
                return set()
            name = token[1]
            fi = summary["from_imports"].get(name)
            if fi:
                home = self.mesh_of.get(fi[0])
                if home and fi[1] in home["consts"]:
                    return {home["consts"][fi[1]]}
                # one-hop package re-export
                pkg = self.project.by_module.get(fi[0])
                if pkg and fi[1] in pkg["from_imports"]:
                    m2, sym2 = pkg["from_imports"][fi[1]]
                    home2 = self.mesh_of.get(m2)
                    if home2 and sym2 in home2["consts"]:
                        return {home2["consts"][sym2]}
            return set()
        if kind == "param":
            fn, pname = token[1], token[2]
            key = (module, fn, pname)
            seen = seen or set()
            if key in seen:
                return set()
            seen = seen | {key}
            mesh = self.mesh_of.get(module, {})
            plist = mesh.get("params", {}).get(fn, [])
            out: set = set()
            for (caller_mod, b) in self.call_binds.get((module, fn), ()):
                tok = b["kw"].get(pname)
                if tok is None and pname in plist:
                    tok = b["pos"].get(str(plist.index(pname)))
                if tok is not None:
                    out |= self.resolve_token(caller_mod, tok,
                                              depth - 1, seen)
            return out
        return set()

    # -- mesh/wrap facts -----------------------------------------------

    def known_mesh_axes(self) -> set:
        axes: set = set()
        for module, mesh in self.mesh_of.items():
            for m in mesh["meshes"]:
                for tok in m["axes"]:
                    axes |= self.resolve_token(module, tok)
        return axes

    def wraps(self):
        """Every shard_map wrap: (module, summary, wrap dict,
        resolved-axis set or None when any token is unresolved)."""
        out = []
        for s in self.project.summaries:
            mesh = self.mesh_of[s["module"]]
            for w in mesh["shard_maps"]:
                axes: set | None = set()
                for tok in w["axes"]:
                    r = self.resolve_token(s["module"], tok)
                    if not r:
                        axes = None  # partially symbolic: admit all
                        break
                    axes |= r
                if axes is not None and not axes:
                    axes = None  # no P() literals at all
                out.append((s["module"], s, w, axes))
        return out

    def wrap_reach(self):
        """One BFS from every shard_map wrap target: pred map for
        chains, plus per-function admitted axis sets — the UNION over
        every wrap that can reach the function (None = some reaching
        wrap admits anything).  Admitted axes propagate to a fixpoint
        over ALL call edges among reached functions, not just the BFS
        tree: a helper reached from both the vertex-sharded and the
        batch-sharded wrap must admit both axes, or a legitimate
        collective would be falsely convicted."""
        project = self.project
        seeds = []
        wrap_axes: dict = {}
        for module, s, w, axes in self.wraps():
            for t in w["targets"]:
                tgt = project._resolve(s, t)
                if tgt is not None and tgt in project.funcs:
                    seeds.append(tgt)
                    prev = wrap_axes.get(tgt, set())
                    if axes is None or prev is None:
                        wrap_axes[tgt] = None
                    else:
                        wrap_axes[tgt] = prev | axes
        pred = project._reach(seeds)

        def merge(a, b):
            if a is None or b is None:
                return None
            return a | b

        admitted: dict = {k: wrap_axes.get(k, set()) for k in pred}
        changed = True
        while changed:
            changed = False
            for key in pred:
                src = admitted.get(key, set())
                if src is not None and not src:
                    continue  # nothing to propagate yet
                for fn in project.funcs.get(key, ()):
                    for tgt in project._edges_of(key[0], fn):
                        if tgt not in pred:
                            continue
                        merged = merge(admitted.get(tgt, set()), src)
                        if merged != admitted.get(tgt, set()):
                            admitted[tgt] = merged
                            changed = True
        return pred, admitted


def replicated_inventory(summaries) -> list:
    """Every annotated O(nv_total) materialization in the summary set:
    [{rel, line, fn, call, size, scope, reason, snippet}] — the closed,
    justified inventory of per-chip-replicated tables the two-level
    exchange narrowed (``python tools/mesh_audit.py --inventory``
    prints it).  ``scope`` is parsed from the reason's ``scope=<s>;``
    prefix (see :data:`_SCOPE_RE`); an unprefixed reason reports
    ``"global"`` — the scope the two-level contract eliminated, kept
    visible so a regression is one grep away."""
    out = []
    for s in summaries:
        mesh = (s or {}).get("mesh") or {}
        for a in mesh.get("allocs", ()):
            if a.get("replicated_ok"):
                reason = a["replicated_ok"]
                m = _SCOPE_RE.match(reason)
                out.append({
                    "rel": s["rel"], "line": a["line"], "fn": a["fn"],
                    "call": a["call"], "size": a["size"],
                    "scope": m.group(1) if m else "global",
                    "reason": reason[m.end():] if m else reason,
                    "snippet": a["snippet"],
                })
    return sorted(out, key=lambda d: (d["rel"], d["line"]))


# ---------------------------------------------------------------------------
# Rules.

from cuvite_tpu.analysis.callgraph import ProjectRule  # noqa: E402


def _mesh_view(project):
    """One MeshProject + wrap-reach per project pass, shared by the
    three rules (identical inputs -> identical outputs; rebuilding the
    call-bind index and the reach fixpoint three times per lint run is
    pure tax).  Cached on the Project instance, which lives exactly one
    run_project pass."""
    view = getattr(project, "_tier5_view", None)
    if view is None:
        mp = MeshProject(project)
        view = (mp,) + mp.wrap_reach()
        project._tier5_view = view
    return view


def _site_finding(rule, summary, site, message) -> Finding:
    return Finding(rule=rule.id, severity=rule.severity,
                   path=summary["rel"], line=site["line"],
                   message=message, snippet=site["snippet"])


@register
class AxisNameDrift(ProjectRule):
    id = "R023"
    severity = "high"
    title = "collective axis name is not an axis of the meshes whose " \
            "shard_map reaches it (cross-module)"

    def check_project(self, project):
        mp, pred, admitted = _mesh_view(project)
        known = mp.known_mesh_axes()
        for summary in project.summaries:
            mod = summary["module"]
            mesh = mp.mesh_of[mod]
            for c in mesh["collectives"]:
                key = (mod, c["fn"])
                if key not in pred:
                    continue
                axes = mp.resolve_token(mod, c["axis"])
                if not axes:
                    continue  # unresolved: bounded false negative
                chain = project.chain(pred, key)
                bad = sorted(axes - known) if known else []
                if bad:
                    yield _site_finding(
                        self, summary, c,
                        f"{c['call']}(...) uses axis "
                        f"{', '.join(map(repr, bad))} which no mesh in "
                        f"the project constructs (known axes: "
                        f"{sorted(known)}); reached from a shard_map "
                        f"body via {chain} — a renamed/split mesh axis "
                        "leaves this collective deadlocking or crashing "
                        "at trace time")
                    continue
                adm = admitted.get(key, None)
                if adm is not None and adm and not (axes & adm):
                    yield _site_finding(
                        self, summary, c,
                        f"{c['call']}(...) uses axis "
                        f"{sorted(axes)} but every shard_map that "
                        f"reaches it ({chain}) maps only axes "
                        f"{sorted(adm)}: the collective would fail on "
                        "the meshes that actually run this body (the "
                        "two-level ICI/DCN split bug class)")


@register
class WholeProgramCollectiveDivergence(ProjectRule):
    id = "R024"
    severity = "high"
    title = "SPMD collective under a data-dependent branch in code " \
            "reachable from a shard_map body (cross-module)"

    def check_project(self, project):
        mp, pred, _admitted = _mesh_view(project)
        for summary in project.summaries:
            mod = summary["module"]
            for c in mp.mesh_of[mod]["collectives"]:
                if not c.get("divergent"):
                    continue
                key = (mod, c["fn"])
                if key not in pred:
                    continue
                chain = project.chain(pred, key)
                yield _site_finding(
                    self, summary, c,
                    f"collective {c['call']}(...) is issued under a "
                    f"branch that can differ across shards/hosts "
                    f"({c['divergent']}), and the function is reachable "
                    f"from a shard_map body ({chain}): shards "
                    "disagreeing on the collective sequence is the "
                    "canonical SPMD deadlock (per-file host-wrapper "
                    "cases stay R004's); issue the collective "
                    "unconditionally or branch on a trace-time static")


@register
class ReplicationAudit(ProjectRule):
    id = "R025"
    severity = "high"
    title = "O(nv_total)-per-chip buffer materialized in shard_map-" \
            "reachable code without a replicated-ok justification"

    def check_project(self, project):
        mp, pred, _admitted = _mesh_view(project)
        for summary in project.summaries:
            mod = summary["module"]
            for a in mp.mesh_of[mod]["allocs"]:
                if a.get("replicated_ok"):
                    continue
                key = (mod, a["fn"])
                if key not in pred:
                    continue
                chain = project.chain(pred, key)
                what = ("all_gather replicates the gathered axis"
                        if a["size"] == "all_gather"
                        else f"size scales with {a['size']}")
                yield _site_finding(
                    self, summary, a,
                    f"{a['call']}(...) materializes a device buffer "
                    f"with no sharded axis inside shard_map-reachable "
                    f"code ({chain}); {what}, i.e. O(nv_total) bytes "
                    "PER CHIP — the exact class round-8 measured as "
                    "the sparse-cutover wall.  Shard it, or justify "
                    "with '# graftlint: replicated-ok=<reason>' on "
                    "this line (the annotation feeds the closed "
                    "replication inventory, tools/mesh_audit.py "
                    "--inventory)")
