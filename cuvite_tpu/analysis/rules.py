"""graftlint per-file rule set R001..R016 + R022 + R029 (see
ANALYSIS.md for the catalogue; R017-R021 live in the project-tier
modules).

Each rule targets a hazard class this codebase has actually hit (or is
one refactor away from hitting): host syncs inside jitted code, jit
recompile traps, 64-bit dtype drift into the 32-bit device path,
collective-order divergence across hosts, mutation of caller-owned
buffers, non-exact reductions feeding modularity, unbounded child
processes in tools, host-global side effects in test fixtures, network
access outside the workloads fetch path (or without checksum
verification), device->host pulls in phase-transition code, Pallas
block shapes not derived from the static width-ladder constants, and
bench timing windows that close without forcing device completion,
full-slab sorts in coarsen/kernels outside the sanctioned coalesce
fallback chokepoint, compile/upload-per-job traps in serving queue
loops, bucket-plan construction inside serve/ dispatch loops (planning
belongs at pack time), direct wall-clock reads in serve/ outside
the injectable-clock plumbing (untestable deadlines), and resident-slab
mutation in stream//serve/ outside the apply_delta_slab chokepoint
(the donor-buffer aliasing trap).

Rules are heuristic by design: they trade completeness for a near-zero
false-positive rate on idiomatic code, and every remaining intentional
violation is handled by an inline ``# graftlint: disable=R###`` with a
justification comment, or by the checked-in baseline.
"""

from __future__ import annotations

import ast

from cuvite_tpu.analysis.engine import (
    _JIT_NAMES,
    Rule,
    dotted,
    register,
)

# Directories whose modules run (or build arrays for) the device path.
DEVICE_PATH_PREFIXES = (
    "cuvite_tpu/louvain/",
    "cuvite_tpu/kernels/",
    "cuvite_tpu/ops/",
)

# Host-blocking calls that must not appear in jit-reachable code: each
# one forces a device->host transfer (or a trace-time concretization
# error that only fires on the first run of a rarely-taken path).
HOST_SYNC_ATTRS = {"item", "block_until_ready", "tolist"}
HOST_SYNC_CALLS = {
    "float", "int", "bool",
    "np.asarray", "numpy.asarray", "np.array", "numpy.array",
    "jax.device_get",
}

# Host-side collective wrappers (cuvite_tpu.comm.multihost) plus the jax
# primitives they wrap: every host must reach these in the same order.
COLLECTIVE_NAMES = {
    "process_allgather", "allgather_varlen", "allreduce_sum_host",
    "allreduce_max_host", "gather_global", "broadcast_one_to_all",
    "sync_global_devices", "broadcast_host_local_array",
}

# Condition calls that are uniform across hosts by construction, so
# branching on them cannot diverge collective order.
UNIFORM_CONDITION_CALLS = {
    "is_distributed", "len", "isinstance", "issubclass", "bool", "int",
    "jax.process_count", "process_count", "hasattr",
}


def _in_device_path(sf) -> bool:
    return sf.rel.startswith(DEVICE_PATH_PREFIXES)


def _nodes_of_function(sf, info):
    """Nodes lexically inside ``info``'s body but not inside a nested
    def (those belong to the nested function)."""
    for node in ast.walk(info.node):
        if node is not info.node and sf.enclosing_function(node) is info:
            yield node


@register
class HostSyncInJit(Rule):
    id = "R001"
    severity = "high"
    title = "host-sync call reachable from a @jax.jit function"

    def check(self, sf):
        for node in sf.walk():
            if not isinstance(node, ast.Call):
                continue
            info = sf.enclosing_function(node)
            if info is None or not info.jit_reachable:
                continue
            name = dotted(node.func)
            label = None
            if name in HOST_SYNC_CALLS:
                label = f"{name}()"
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr in HOST_SYNC_ATTRS \
                    and not node.args:
                label = f".{node.func.attr}()"
            if label is None:
                continue
            yield self.finding(
                sf, node,
                f"{label} in '{info.name}' (reachable from @jax.jit): "
                "forces a blocking device->host sync, or a trace-time "
                "concretization error on the first traced run")


def _is_none_check(test: ast.expr) -> bool:
    """``<expr> is None`` / ``is not None`` — trace-time structural
    dispatch (an operand is either a tracer or literally None), never a
    branch on traced VALUES, so R002 exempts it wholesale."""
    return (isinstance(test, ast.Compare)
            and len(test.ops) == 1
            and isinstance(test.ops[0], (ast.Is, ast.IsNot))
            and isinstance(test.comparators[0], ast.Constant)
            and test.comparators[0].value is None)


@register
class RecompileTrap(Rule):
    id = "R002"
    severity = "medium"
    title = "jit recompile trap (non-literal statics / traced branching)"

    def _check_statics(self, sf):
        from cuvite_tpu.analysis.engine import (
            _const_ints, _const_names, _jit_call,
        )

        for node in sf.walk():
            call = _jit_call(node)
            if call is None:
                continue
            for kw in call.keywords:
                if kw.arg == "static_argnames":
                    ok = _const_names(kw.value) is not None \
                        or isinstance(kw.value, ast.Name)
                    what = "static_argnames"
                elif kw.arg == "static_argnums":
                    ok = _const_ints(kw.value) is not None \
                        or isinstance(kw.value, ast.Name)
                    what = "static_argnums"
                else:
                    continue
                if not ok:
                    yield self.finding(
                        sf, kw.value,
                        f"{what} is not a literal int/str (tuple): "
                        "computed statics hide unhashable or array "
                        "values, which either crash dispatch or key the "
                        "compile cache on object identity (a recompile "
                        "per call)")

    def _check_branches(self, sf):
        for info in sf.functions:
            if not info.is_jit:
                continue
            traced = set(info.params) - info.static_names
            if not traced:
                continue
            for node in _nodes_of_function(sf, info):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                if _is_none_check(node.test):
                    continue
                names = {n.id for n in ast.walk(node.test)
                         if isinstance(n, ast.Name)}
                hot = sorted(names & traced)
                if hot:
                    yield self.finding(
                        sf, node,
                        f"Python branch on traced argument(s) "
                        f"{', '.join(hot)} of jitted '{info.name}': "
                        "concretizes the tracer (TracerBoolConversionError"
                        " at best, silent per-value recompiles via "
                        "static fallback at worst); use lax.cond/select "
                        "or mark the argument static")

    def check(self, sf):
        yield from self._check_statics(sf)
        yield from self._check_branches(sf)


_J64_ATTRS = {"jnp.int64", "jnp.float64", "jnp.uint64",
              "jax.numpy.int64", "jax.numpy.float64", "jax.numpy.uint64"}
_J64_NP_ATTRS = {"np.int64", "np.float64", "np.uint64",
                 "numpy.int64", "numpy.float64", "numpy.uint64"}
_J64_STRINGS = {"int64", "float64", "uint64"}
_JNP_PREFIXES = ("jnp.", "jax.numpy.")


def _is_64_dtype_arg(node: ast.AST) -> str | None:
    """'int64'-style label if ``node`` denotes a 64-bit dtype (string
    constant or np/numpy attribute; jnp attributes are reported by the
    attribute branch already), else None."""
    if isinstance(node, ast.Constant) and node.value in _J64_STRINGS:
        return str(node.value)
    name = dotted(node)
    if name in _J64_NP_ATTRS:
        return name
    return None


@register
class DtypeWidthDrift(Rule):
    id = "R003"
    severity = "medium"
    title = "64-bit device dtype in a 32-bit device-path module"

    def check(self, sf):
        if not _in_device_path(sf):
            return
        for node in sf.walk():
            if isinstance(node, ast.Attribute) and dotted(node) in _J64_ATTRS:
                yield self.finding(
                    sf, node,
                    f"{dotted(node)} in a device-path module: without "
                    "jax_enable_x64 this silently degrades to 32-bit "
                    "(corrupting packed keys / ids), and with it the "
                    "whole graph pays 2x memory; route widths through "
                    "the dtype policy (core.types) instead")
            elif isinstance(node, ast.Call):
                fname = dotted(node.func) or ""
                if fname.startswith(_JNP_PREFIXES):
                    for kw in node.keywords:
                        label = kw.arg == "dtype" \
                            and _is_64_dtype_arg(kw.value)
                        if label:
                            yield self.finding(
                                sf, kw.value,
                                f"dtype={label} passed to {fname} in a "
                                "device-path module: defeats the 32-bit "
                                "graph mode (see R003 notes in "
                                "ANALYSIS.md)")
                # .astype(<64-bit>) where the receiver is itself a jnp
                # construction — host np arrays cast with .astype(np.int64)
                # are plan-building code and stay out of scope.
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "astype" and node.args:
                    recv = node.func.value
                    rname = dotted(recv.func) \
                        if isinstance(recv, ast.Call) else dotted(recv)
                    label = _is_64_dtype_arg(node.args[0])
                    if label and rname and rname.startswith(_JNP_PREFIXES):
                        yield self.finding(
                            sf, node,
                            f".astype({label}) on a {rname} result in a "
                            "device-path module: defeats the 32-bit "
                            "graph mode (see R003 notes in ANALYSIS.md)")


def _condition_is_divergent(test: ast.expr) -> str | None:
    """Why a branch condition can differ between hosts, or None.

    Divergent: references process_index / process_id, or contains any
    call other than the known host-uniform predicates (a call result is
    runtime data the linter cannot prove replicated)."""
    for n in ast.walk(test):
        name = dotted(n) if isinstance(n, (ast.Name, ast.Attribute)) else None
        if name and name.split(".")[-1] in ("process_index", "process_id"):
            return f"condition references {name}"
        if isinstance(n, ast.Call):
            cname = dotted(n.func) or "<expr>"
            if cname.split(".")[-1] not in UNIFORM_CONDITION_CALLS \
                    and cname not in UNIFORM_CONDITION_CALLS:
                return f"condition depends on {cname}(...)"
    return None


@register
class CollectiveOrderDivergence(Rule):
    id = "R004"
    severity = "high"
    title = "collective call under a data-dependent or fallible branch"

    def check(self, sf):
        for node in sf.walk():
            if not isinstance(node, ast.Call):
                continue
            fname = dotted(node.func) or ""
            if fname.split(".")[-1] not in COLLECTIVE_NAMES:
                continue
            info = sf.enclosing_function(node)
            boundary = info.node if info is not None else None
            child = node
            for anc in sf.ancestors(node):
                if anc is boundary:
                    break
                if isinstance(anc, ast.Try):
                    yield self.finding(
                        sf, node,
                        f"collective {fname}() inside a try block: an "
                        "exception on one host skips its remaining "
                        "collectives while peers block in them — "
                        "deadlock, not an error message; hoist the "
                        "collective out or convert the failure into a "
                        "value every host agrees on")
                    break
                if isinstance(anc, (ast.If, ast.While)) \
                        and child is not anc.test:
                    why = _condition_is_divergent(anc.test)
                    if why:
                        yield self.finding(
                            sf, node,
                            f"collective {fname}() under a branch that "
                            f"may differ between hosts ({why}): hosts "
                            "disagreeing on whether to issue a "
                            "collective is the canonical multi-host "
                            "deadlock; make the condition a replicated "
                            "value or issue the collective "
                            "unconditionally")
                        break
                child = anc


_INPLACE_METHODS = {"fill", "sort", "resize", "partition", "put", "setfield"}


@register
class CallerBufferMutation(Rule):
    id = "R005"
    severity = "medium"
    title = "mutation of a caller-owned buffer argument"

    def check(self, sf):
        for info in sf.functions:
            # Pallas kernels receive mutable Refs — writing *_ref output
            # params is their calling convention, not a hazard.
            params = {p for p in info.params
                      if p not in ("self", "cls")
                      and not p.endswith("_ref")}
            if not params:
                continue
            for node in _nodes_of_function(sf, info):
                yield from self._check_node(sf, info, params, node)

    def _check_node(self, sf, info, params, node):
        def is_param(expr):
            return isinstance(expr, ast.Name) and expr.id in params

        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                # p.flags.writeable = ... — the caller's array changes
                # behaviour (later writes raise) as a side effect.
                if isinstance(tgt, ast.Attribute) \
                        and tgt.attr == "writeable" \
                        and isinstance(tgt.value, ast.Attribute) \
                        and tgt.value.attr == "flags" \
                        and is_param(tgt.value.value):
                    yield self.finding(
                        sf, node,
                        f"'{info.name}' flips writeable on its argument "
                        f"'{tgt.value.value.id}': the caller's buffer "
                        "changes behaviour behind its back — document "
                        "the contract and freeze the base chain, or "
                        "copy instead")
                elif isinstance(tgt, ast.Subscript) and is_param(tgt.value):
                    yield self.finding(
                        sf, node,
                        f"'{info.name}' writes in place into its "
                        f"argument '{tgt.value.id}': callers retaining "
                        "the array observe the mutation (and zero-copy "
                        "device aliases of it go stale)")
        elif isinstance(node, ast.AugAssign):
            tgt = node.target
            if isinstance(tgt, ast.Subscript) and is_param(tgt.value):
                yield self.finding(
                    sf, node,
                    f"'{info.name}' updates its argument "
                    f"'{tgt.value.id}' in place")
        elif isinstance(node, ast.Call):
            fname = dotted(node.func) or ""
            if fname in ("np.copyto", "numpy.copyto") and node.args \
                    and is_param(node.args[0]):
                yield self.finding(
                    sf, node,
                    f"'{info.name}' np.copyto()s into its argument "
                    f"'{node.args[0].id}'")
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _INPLACE_METHODS \
                    and is_param(node.func.value):
                yield self.finding(
                    sf, node,
                    f"'{info.name}' calls .{node.func.attr}() on its "
                    f"argument '{node.func.value.id}' (in-place)")


_MOD_NAME = ("mod", "modularity", "q")
_SUM_CALLS = {"segment_sum", "sum"}
# Substrings of the assigned expression that mark the exact path (the
# ds_* double-single helpers / ops.exactsum); accum_dtype-style params
# are checked separately on the enclosing function.
_EXACT_MARKERS = ("ds_", "exactsum")


def _is_mod_name(name: str) -> bool:
    low = name.lower()
    if "modularity" in low:
        return True
    parts = low.split("_")
    return parts[0] in _MOD_NAME or parts[-1] in _MOD_NAME


@register
class InexactModularityReduction(Rule):
    id = "R006"
    severity = "medium"
    title = "non-exact reduction feeding a modularity accumulator"

    def check(self, sf):
        if not (sf.rel.startswith("cuvite_tpu/louvain/")
                or sf.rel.startswith("cuvite_tpu/evaluate/")):
            return
        for node in sf.walk():
            if not isinstance(node, ast.Assign):
                continue
            names = [t.id for t in node.targets if isinstance(t, ast.Name)]
            if not any(_is_mod_name(n) for n in names):
                continue
            sub = ast.dump(node.value)
            if any(m in sub for m in _EXACT_MARKERS):
                continue  # already on the exact path
            info = sf.enclosing_function(node)
            if info is not None and any(
                    "accum" in p or p == "adt" for p in info.params):
                continue  # dtype-policy-aware: width chosen by caller
            for call in ast.walk(node.value):
                if not isinstance(call, ast.Call):
                    continue
                fname = dotted(call.func) or (
                    call.func.attr if isinstance(call.func, ast.Attribute)
                    else "")
                if fname.split(".")[-1] in _SUM_CALLS:
                    yield self.finding(
                        sf, node,
                        f"modularity accumulator '{names[0]}' fed by "
                        f"{fname.split('.')[-1]}() without the exact "
                        "path: f32 tree sums lose ~log2(n)*2^-24 "
                        "relative — enough to flip the 1e-6 convergence "
                        "test at scale; use ops.exactsum (ds32) or an "
                        "accum_dtype-aware reduction")
                    break


_SUBPROCESS_BLOCKING = {
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output",
}


@register
class SubprocessNoTimeout(Rule):
    id = "R007"
    severity = "high"
    title = "blocking subprocess call without a timeout in tools/"

    def check(self, sf):
        if not sf.rel.startswith("tools/"):
            return
        for node in sf.walk():
            if not isinstance(node, ast.Call):
                continue
            fname = dotted(node.func)
            if fname not in _SUBPROCESS_BLOCKING:
                continue
            if any(kw.arg == "timeout" for kw in node.keywords):
                continue
            if any(kw.arg is None for kw in node.keywords):
                continue  # **kwargs may carry a timeout: cannot prove
            yield self.finding(
                sf, node,
                f"{fname}() without timeout=: a hung child (TPU client "
                "handshake, OOM-thrash) wedges the whole tool run "
                "forever; pass a generous timeout and handle "
                "TimeoutExpired loudly")


_EMPTYISH = (None, "", "0")


def _env_get_polarity(sf, call: ast.Call, test: ast.expr):
    """How the env-get GATES ``test``: True — the branch cannot be taken
    unless the variable is set to an opt-in value; False — the branch
    cannot be taken WHILE it is set (``not get(X)``: the else branch is
    then the opted-in one); None — cannot prove either (an ``or`` arm or
    truthy default lets the branch fire regardless, and unknown
    constructs are treated the same, conservatively).

    Polarity flips: ``not`` flips; ``== / is`` against None/''/'0' flips
    (``get(X) is None`` means NOT set); ``!= / is not`` against those
    keeps; against any other constant, equality keeps (``== '1'`` is an
    explicit opt-in value) and inequality flips (``!= '1'`` is true
    whenever the var is unset — opt-out, rephrased).  Only ``and``
    conjunctions may sit between the get and the test root."""
    defaults = list(call.args[1:2]) + [
        kw.value for kw in call.keywords if kw.arg == "default"]
    for d in defaults:
        if not (isinstance(d, ast.Constant) and d.value in _EMPTYISH):
            return None  # truthy (or unprovable) default: true while unset
    positive = True
    if call is test:
        return positive
    child = call
    for anc in sf.ancestors(call):
        if isinstance(anc, ast.UnaryOp) and isinstance(anc.op, ast.Not):
            positive = not positive
        elif isinstance(anc, ast.Compare):
            if not (anc.comparators and child is anc.left
                    and isinstance(anc.comparators[0], ast.Constant)):
                return None  # yoda/chained forms: cannot prove gating
            op, cmp_ = anc.ops[0], anc.comparators[0]
            emptyish = cmp_.value in _EMPTYISH
            if isinstance(op, (ast.Eq, ast.Is)):
                positive ^= emptyish
            elif isinstance(op, (ast.NotEq, ast.IsNot)):
                positive ^= not emptyish
            else:
                return None
        elif isinstance(anc, ast.BoolOp):
            if not isinstance(anc.op, ast.And):
                return None  # an `or` arm bypasses the env var
        else:
            return None  # wrapped in a call/ifexp/...: cannot prove
        child = anc
        if anc is test:
            break
    return positive


def _opt_in_gated(sf, node) -> bool:
    """True if an ancestor ``if`` gates ``node`` on an os.environ.get /
    os.getenv whose polarity matches the BRANCH holding ``node``: the
    ``if`` body needs positive polarity (the opt-in idiom), the ``else``
    branch needs negative (the else of ``if not get(X)`` runs only when
    X is set).  Everything else — opt-OUT spellings (``not get(X)``,
    ``get(X) is None``, ``get(X) != '1'``), the else of an opt-IN check
    (runs by default when unset!), truthy defaults — does not count."""
    prev = node
    for anc in sf.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            break
        if isinstance(anc, ast.If) and prev is not anc.test:
            in_body = any(prev is s for s in anc.body)
            in_orelse = any(prev is s for s in anc.orelse)
            for n in ast.walk(anc.test):
                if isinstance(n, ast.Call):
                    cname = dotted(n.func) or ""
                    if cname not in ("os.environ.get", "os.getenv") \
                            and not cname.endswith("environ.get"):
                        continue
                    pol = _env_get_polarity(sf, n, anc.test)
                    if (in_body and pol is True) \
                            or (in_orelse and pol is False):
                        return True
        prev = anc
    return False


@register
class HostGlobalTestSideEffect(Rule):
    id = "R008"
    severity = "high"
    title = "host-global side effect in tests without opt-in gating"

    def check(self, sf):
        if not (sf.rel.startswith("tests/")
                or sf.rel.endswith("conftest.py")):
            return
        for node in sf.walk():
            if not isinstance(node, ast.Call):
                continue
            fname = dotted(node.func)
            if fname == "open":
                target = node.args[0] if node.args else None
                mode = None
                if len(node.args) > 1:
                    mode = node.args[1]
                for kw in node.keywords:
                    if kw.arg == "mode":
                        mode = kw.value
                if not (isinstance(target, ast.Constant)
                        and isinstance(target.value, str)
                        and target.value.startswith("/proc/sys")):
                    continue
                if not (isinstance(mode, ast.Constant)
                        and isinstance(mode.value, str)
                        and any(c in mode.value for c in "wa+")):
                    continue
                if _opt_in_gated(sf, node):
                    continue
                yield self.finding(
                    sf, node,
                    f"sysctl write ({target.value}) in a test fixture "
                    "without an opt-in env gate: a HOST-GLOBAL knob "
                    "silently changed for everything else on the "
                    "machine; gate it on an explicit CUVITE_*=1 opt-in "
                    "and restore the prior value at session finish")
            elif fname == "os.putenv":
                if _opt_in_gated(sf, node):
                    continue
                yield self.finding(
                    sf, node,
                    "os.putenv() in tests bypasses os.environ "
                    "bookkeeping (leaks into every child, invisible to "
                    "os.environ readers); assign os.environ[...] "
                    "instead, or gate behind an opt-in")


# The ONE module allowed to open network connections: the workloads
# dataset registry's fetch path (which must checksum what it downloads).
NETWORK_ALLOWED_FILE = "cuvite_tpu/workloads/registry.py"

# Call names that open a network connection.  Matched on the dotted name
# (or its last attribute for the bare-import spellings).
_NET_CALL_NAMES = {
    "urlopen", "urlretrieve",  # urllib.request.* / bare from-imports
    "socket.create_connection", "ftplib.FTP",
    "http.client.HTTPConnection", "http.client.HTTPSConnection",
}
_NET_CALL_PREFIXES = ("urllib.request.", "requests.")

# Evidence that a function verifies what it downloaded: any call whose
# name mentions a digest or an explicit checksum/verify helper.
_CHECKSUM_MARKERS = ("sha256", "sha512", "sha1", "md5", "blake2",
                     "checksum", "verify")

_SUBPROCESS_ANY = _SUBPROCESS_BLOCKING | {"subprocess.Popen"}
_DOWNLOADER_TOOLS = {"curl", "wget", "aria2c", "scp", "rsync"}


def _is_net_call(name: str | None) -> bool:
    if not name:
        return False
    return (name in _NET_CALL_NAMES
            or name.split(".")[-1] in ("urlopen", "urlretrieve")
            or name.startswith(_NET_CALL_PREFIXES))


def _subprocess_downloader(node: ast.Call) -> str | None:
    """The downloader binary name if this subprocess call shells out to
    one (list or string first argument), else None."""
    if not node.args:
        return None
    arg = node.args[0]
    cands = []
    if isinstance(arg, (ast.List, ast.Tuple)):
        cands = [el.value for el in arg.elts
                 if isinstance(el, ast.Constant) and isinstance(el.value, str)]
    elif isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        cands = arg.value.split()
    for c in cands:
        base = c.rsplit("/", 1)[-1]
        if base in _DOWNLOADER_TOOLS:
            return base
    return None


@register
class NetworkOutsideRegistry(Rule):
    id = "R009"
    severity = "high"
    title = "network call outside the workloads fetch path, or a " \
            "download without checksum verification"

    def check(self, sf):
        for node in sf.walk():
            if not isinstance(node, ast.Call):
                continue
            fname = dotted(node.func)
            if _is_net_call(fname):
                if sf.rel != NETWORK_ALLOWED_FILE:
                    yield self.finding(
                        sf, node,
                        f"network call {fname}() outside "
                        f"{NETWORK_ALLOWED_FILE}: dataset fetches live in "
                        "the registry (offline rigs must fall back to the "
                        "synthesizer, and every download must be "
                        "checksum-verified there)")
                    continue
                info = sf.enclosing_function(node)
                calls = info.calls if info is not None else set()
                if not any(any(m in c.lower() for m in _CHECKSUM_MARKERS)
                           for c in calls):
                    yield self.finding(
                        sf, node,
                        f"download via {fname}() without checksum "
                        "verification in the same function: a truncated "
                        "or tampered artifact would convert silently; "
                        "hash the stream (hashlib.sha256) and verify "
                        "before use")
            elif dotted(node.func) in _SUBPROCESS_ANY:
                tool = _subprocess_downloader(node)
                if tool is not None:
                    yield self.finding(
                        sf, node,
                        f"subprocess download via '{tool}': shelling out "
                        "skips the registry's checksum verification and "
                        "offline fallback; use "
                        "cuvite_tpu.workloads.registry.fetch instead")


# Modules that carry device-resident phase-transition state (the slab
# that coarsen/device.py keeps in HBM across phases).  A stray host
# materialization here re-introduces the O(E) PCIe round-trip the device
# coarsener exists to remove — the regression class ISSUE 3 closed.
PHASE_TRANSITION_PREFIXES = (
    "cuvite_tpu/louvain/",
    "cuvite_tpu/coarsen/",
)

# Call spellings that pull a device array to the host wholesale.
_HOST_PULL_CALLS = {"jax.device_get"}
# np.asarray/np.array of a bare name that follows the device-array naming
# convention in these modules (slab/label arrays are *_d / *_dev /
# labels*).  Attributes and other expressions are out of scope: host plan
# arrays are routinely np.asarray'd during plan construction, and flagging
# them would bury the signal (near-zero-false-positive contract).
_HOST_MATERIALIZE_CALLS = {"np.asarray", "numpy.asarray",
                           "np.array", "numpy.array"}
_DEVICE_NAME_SUFFIXES = ("_dev", "_d")
_DEVICE_NAME_PREFIXES = ("labels",)


@register
class PallasLiteralBlockShape(Rule):
    id = "R011"
    severity = "medium"
    title = "Pallas BlockSpec block shape with a hard-coded dimension"

    # Unit dims are layout plumbing ((1, tile) vectors, (D, 1) rows), not a
    # tile-size decision; anything else must be a NAME bound to the static
    # width-ladder constants (DEFAULT_BUCKETS-derived D, the VMEM-budgeted
    # tile, LANE) so a ladder retune cannot leave a kernel silently
    # recompiling per width or overflowing VMEM with a stale literal.
    _ALLOWED_LITERALS = (1,)

    def check(self, sf):
        if not _in_device_path(sf):
            return
        for node in sf.walk():
            if not isinstance(node, ast.Call):
                continue
            fname = dotted(node.func) or ""
            if fname.split(".")[-1] != "BlockSpec":
                continue
            if not node.args:
                continue  # memory_space-only spec: no block shape
            shape = node.args[0]
            if not isinstance(shape, (ast.Tuple, ast.List)):
                continue
            for el in shape.elts:
                if isinstance(el, ast.Constant) \
                        and isinstance(el.value, int) \
                        and el.value not in self._ALLOWED_LITERALS:
                    yield self.finding(
                        sf, el,
                        f"BlockSpec block dimension {el.value} is a "
                        "hard-coded literal: block shapes must be derived "
                        "from the static width-ladder constants "
                        "(DEFAULT_BUCKETS widths / PALLAS_MAX_WIDTH / "
                        "LANE / the VMEM-budgeted tile) — a stale literal "
                        "silently recompiles per width class or blows "
                        "VMEM when the ladder is retuned")


@register
class DeviceArrayHostPull(Rule):
    id = "R010"
    severity = "medium"
    title = "device->host pull of a device-resident array in " \
            "phase-transition code"

    def check(self, sf):
        if not sf.rel.startswith(PHASE_TRANSITION_PREFIXES):
            return
        for node in sf.walk():
            if not isinstance(node, ast.Call):
                continue
            fname = dotted(node.func)
            if fname in _HOST_PULL_CALLS:
                yield self.finding(
                    sf, node,
                    f"{fname}() in a phase-transition module: a device->"
                    "host pull here puts O(E)/O(V) bytes back on the PCIe "
                    "path the device-resident coarsening removed; keep "
                    "the slab in HBM.  Scalar/stat syncs and THE final "
                    "label gather are the allowed exceptions — carry an "
                    "inline '# graftlint: disable=R010' with a "
                    "justification")
            elif fname in _HOST_MATERIALIZE_CALLS and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Name) and (
                        arg.id.endswith(_DEVICE_NAME_SUFFIXES)
                        or arg.id.startswith(_DEVICE_NAME_PREFIXES)):
                    yield self.finding(
                        sf, node,
                        f"{fname}({arg.id}) materializes a device-"
                        "resident array (by naming convention) on the "
                        "host inside phase-transition code; gather "
                        "scalars instead, or justify with an inline "
                        "disable (the final label gather is the "
                        "allowlisted case)")


# ---------------------------------------------------------------------------
# R012: async-dispatch mistiming in bench/tool timing windows (ISSUE 6).
# Every recorded perf number comes from a time.perf_counter() pair in
# tools/ or the bench harness; jax dispatch is ASYNC, so a window that
# directly dispatches device work and closes without forcing completion
# records launch latency, not execution time (the round-8 exchange
# microbenchmark was nearly rewritten with exactly this bug).

_TIMING_SCOPE_PREFIX = "tools/"
_TIMING_SCOPE_FILES = ("cuvite_tpu/workloads/bench.py",)
_PERF_COUNTER_CALLS = {"time.perf_counter", "perf_counter"}
# Evidence the window forces device completion (or reads the value back,
# which blocks just as hard — the tools prefer real readbacks because
# block_until_ready is unreliable over the axon tunnel).
_TIMING_SYNC_CALLS = {
    "float", "int", "bool",
    "np.asarray", "numpy.asarray", "np.array", "numpy.array",
    "jax.device_get", "jax.block_until_ready", "block_until_ready",
}
_TIMING_SYNC_ATTRS = {"block_until_ready", "item", "tolist"}
# Direct device-dispatch evidence.  Conservative by design: jnp ops,
# explicit uploads, and in-file jit-bound names.  Calls into opaque
# callables (louvain_phases, a passed-in fn) are NOT flagged — the
# callee may sync internally, and flagging them would bury the signal.
_DISPATCH_PREFIXES = ("jnp.", "jax.numpy.")
_DISPATCH_CALLS = {"jax.device_put"}


@register
class UnsyncedTimingWindow(Rule):
    id = "R012"
    severity = "medium"
    title = "perf_counter timing window closes without forcing device " \
            "completion"

    def _jit_names(self, sf) -> set:
        names = {info.name for info in sf.functions if info.is_jit}
        names.update(sf.jit_wrapped)
        for node in sf.walk():
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and dotted(node.value.func) in _JIT_NAMES:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
        return names

    def check(self, sf):
        if not (sf.rel.startswith(_TIMING_SCOPE_PREFIX)
                or sf.rel in _TIMING_SCOPE_FILES):
            return
        jit_names = self._jit_names(sf)
        opens: dict = {}    # (scope id, var name) -> [linenos]
        closes: list = []   # (scope, var name, BinOp node)
        calls: dict = {}    # scope id -> [Call nodes]
        for node in sf.walk():
            scope = sf.enclosing_function(node)
            key = id(scope)
            if isinstance(node, ast.Call):
                calls.setdefault(key, []).append(node)
                continue
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and dotted(node.value.func) in _PERF_COUNTER_CALLS:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        opens.setdefault((key, t.id), []).append(
                            node.lineno)
            elif isinstance(node, ast.BinOp) \
                    and isinstance(node.op, ast.Sub) \
                    and isinstance(node.left, ast.Call) \
                    and dotted(node.left.func) in _PERF_COUNTER_CALLS \
                    and isinstance(node.right, ast.Name):
                closes.append((key, node.right.id, node))
        for key, var, close in closes:
            begins = [ln for ln in opens.get((key, var), ())
                      if ln < close.lineno]
            if not begins:
                continue  # window opened elsewhere (param, outer scope)
            begin = max(begins)
            inside = [c for c in calls.get(key, ())
                      if begin < c.lineno < close.lineno]
            dispatch = None
            last_dispatch_ln = None
            sync_lns = []
            for c in inside:
                fname = dotted(c.func) or ""
                if fname in _TIMING_SYNC_CALLS or (
                        isinstance(c.func, ast.Attribute)
                        and c.func.attr in _TIMING_SYNC_ATTRS):
                    # end_lineno: a wrapped readback whose argument
                    # spans lines (block_until_ready(\n jnp.dot(...)))
                    # still encloses the dispatch it forces.
                    sync_lns.append(getattr(c, "end_lineno", None)
                                    or c.lineno)
                    continue
                if fname.startswith(_DISPATCH_PREFIXES) \
                        or fname in _DISPATCH_CALLS \
                        or (isinstance(c.func, ast.Name)
                            and c.func.id in jit_names):
                    dispatch = dispatch or fname or c.func.id
                    if last_dispatch_ln is None \
                            or c.lineno > last_dispatch_ln:
                        last_dispatch_ln = c.lineno
            # Sync evidence must not PRECEDE the last dispatch: a
            # readback before the dispatch forces nothing, and bare
            # int()/float() on host values are everywhere in bench code.
            # >= keeps same-line wrapping (float(jnp.dot(...))) clean.
            synced = dispatch is not None and any(
                ln >= last_dispatch_ln for ln in sync_lns)
            if dispatch and not synced:
                yield self.finding(
                    sf, close,
                    f"timing window ({var} opened line {begin}) times "
                    f"the device dispatch '{dispatch}' but closes "
                    "without forcing completion (block_until_ready / a "
                    "readback): jax dispatch is async, so this records "
                    "launch latency, not execution time")


# ---------------------------------------------------------------------------
# R013: the coalesce sort tax must not creep back (ISSUE 8).
# BASELINE.md round-7 measured the full-slab lax.sort as THE cost of
# device-resident coarsening (coarsen_s 3.4 s -> 65.0 s at scale 20 on
# CPU: above ~2^15 padded vertices the packed int32 key no longer fits
# and lax.sort degrades to its slowest variadic comparator).  The ONLY
# sanctioned full-slab sort for the coalesce is the fallback chokepoint
# ops/segment.py::coalesced_runs (via sort_edges_by_vertex_comm or
# sort_edges_msd), which reports its engagement as bench coverage
# (`coalesce_kernel`).  A new direct sort in coarsen/ or kernels/ would
# bypass both the dense seg_coalesce engines and the coverage
# accounting — silently re-imposing the tax.  The scope deliberately
# covers the ISSUE-19 modules: the device re-binner (coarsen/rebin.py)
# and the sort-free hash coalesce (kernels/seg_coalesce.py::hash_emit)
# exist precisely to AVOID per-phase sorts, so a lax.sort creeping into
# either is the regression this rule is for.

_SLAB_SORT_SCOPE = (
    "cuvite_tpu/coarsen/",
    "cuvite_tpu/kernels/",
)
_SLAB_SORT_CALLS = {
    "jax.lax.sort", "lax.sort",
    "jax.lax.sort_key_val", "lax.sort_key_val",
    "jnp.sort", "jnp.argsort", "jnp.lexsort",
    "jax.numpy.sort", "jax.numpy.argsort", "jax.numpy.lexsort",
}


@register
class SlabSortOutsideChokepoint(Rule):
    id = "R013"
    severity = "high"
    title = "full-slab device sort in coarsen/ or kernels/ outside the " \
            "sanctioned coalesce fallback chokepoint"

    def check(self, sf):
        if not sf.rel.startswith(_SLAB_SORT_SCOPE):
            return
        for node in sf.walk():
            if not isinstance(node, ast.Call):
                continue
            fname = dotted(node.func)
            if fname in _SLAB_SORT_CALLS:
                yield self.finding(
                    sf, node,
                    f"{fname}() in a coarsen/kernel module: full-slab "
                    "sorts are the round-7 coarsening tax and live ONLY "
                    "behind ops/segment.coalesced_runs (the sanctioned "
                    "fallback chokepoint, whose engagement is reported "
                    "as bench coverage); route through it — or carry an "
                    "inline '# graftlint: disable=R013' with a "
                    "justification for a genuinely non-slab sort")


# ---------------------------------------------------------------------------
# R014: compile-per-job / upload-per-job traps in serving queue loops
# (ISSUE 9).  The batched serving win rests on ONE compiled program per
# (slab class, B) and ONE device placement per packed batch — both live
# in louvain/batched.py at module scope.  A `jax.jit`/`jax.vmap` built
# inside a serve/ queue loop creates a FRESH callable per iteration
# (jit caches per callable identity, so every job recompiles), and a
# per-job `jax.device_put` re-uploads what the batched driver would
# place once per batch.  Either silently erases the amortization the
# subsystem exists for, without changing any result — exactly the class
# of regression a lint must catch, because no test output changes.

_SERVE_SCOPE = ("cuvite_tpu/serve/",)
# The PACKER path (ISSUE 20): the pack/prepare/unpack stage functions
# of the batched driver and the slab packers hold the same per-batch
# amortization contract as the serve/ queue loops — one upload, one
# plan build, zero jit construction per BATCH, however many tenants a
# merged sub-row batch carries.  Scope is per-FUNCTION (pack_*,
# prepare_*, unpack_*), not per-module: the phase loops in the same
# files legitimately run jax calls per iteration.
_PACKER_SCOPE = ("cuvite_tpu/louvain/batched.py", "cuvite_tpu/core/batch.py")
_PACKER_FUNC_PREFIXES = ("pack_", "prepare_", "unpack_")
_SERVE_LOOP_TRAPS = {
    "jax.jit", "jax.vmap", "jax.pmap",
    "jax.device_put", "jnp.asarray", "jax.numpy.asarray",
}


def _serve_loop_calls(sf, names):
    """(node, fname) for every call of ``names`` lexically inside a
    for/while loop of a serve/ module, or of a packer-path function
    (pack_*/prepare_*/unpack_* in the batched driver and slab packer)
    — the shared traversal of the per-job amortization-trap rules
    (R014 compile/upload, R015 plan construction), so their loop/scope
    semantics cannot drift."""
    in_serve = sf.rel.startswith(_SERVE_SCOPE)
    if not in_serve and sf.rel not in _PACKER_SCOPE:
        return
    seen: set = set()
    for loop in sf.walk():
        if not isinstance(loop, (ast.For, ast.AsyncFor, ast.While)):
            continue
        for node in ast.walk(loop):
            if not isinstance(node, ast.Call) or id(node) in seen:
                continue
            fname = dotted(node.func)
            if fname in names:
                if not in_serve:
                    info = sf.enclosing_function(node)
                    if info is None or not info.name.startswith(
                            _PACKER_FUNC_PREFIXES):
                        continue
                seen.add(id(node))
                yield node, fname


@register
class ServeLoopCompileTrap(Rule):
    id = "R014"
    severity = "high"
    title = "jit/vmap construction or per-job device upload inside a " \
            "serve/ queue loop"

    def check(self, sf):
        for node, fname in _serve_loop_calls(sf, _SERVE_LOOP_TRAPS):
            what = ("recompiles per job (jit caches per "
                    "callable identity)"
                    if fname in _JIT_NAMES
                    or fname in ("jax.vmap", "jax.pmap")
                    else "re-uploads per job")
            yield self.finding(
                sf, node,
                f"{fname}() inside a serve/ queue loop {what}: "
                "the batched serving contract is ONE compiled "
                "program per (slab class, B) at module scope "
                "(louvain/batched.py) and ONE device placement "
                "per packed batch (run_batched); hoist it out "
                "of the loop, or justify with an inline "
                "'# graftlint: disable=R014'")


# ---------------------------------------------------------------------------
# R015: bucket-plan construction inside serve/ dispatch loops (ISSUE
# 10).  The batched BUCKETED engine's whole premise is that planning
# happens ONCE per packed batch, at pack time: run_batched calls
# core.batch.batch_bucket_plans (one O(sum E) host pass covering every
# row) before any device work.  A BucketPlan.build /
# build_stacked_plans / batch_bucket_plans call inside a serve/
# for-or-while loop is the plan-PER-JOB trap: it rebuilds O(E) gather
# matrices per tenant per dispatch, turning the pack-time amortization
# into per-job host work — results unchanged, throughput silently
# gone, exactly the regression class R014 guards on the compile side.
# Since ISSUE 19 coarse phases re-bin their plans ON DEVICE inside the
# compiled phase program (coarsen/rebin.py::rebin_plan /
# device_rebin_plan — the sanctioned in-loop planner, deliberately NOT
# in the trap set): a serve loop that calls the host builders per
# phase is silently falling back from that path.

_PLAN_BUILD_CALLS = {
    "BucketPlan.build", "bucketed.BucketPlan.build",
    "build_stacked_plans", "bucketed.build_stacked_plans",
    "batch_bucket_plans", "batch.batch_bucket_plans",
}


@register
class ServeLoopPlanTrap(Rule):
    id = "R015"
    severity = "high"
    title = "bucket-plan construction inside a serve/ dispatch loop " \
            "(planning belongs at pack time)"

    def check(self, sf):
        for node, fname in _serve_loop_calls(sf, _PLAN_BUILD_CALLS):
            yield self.finding(
                sf, node,
                f"{fname}() inside a serve/ dispatch loop builds "
                "bucket plans per job: planning belongs at PACK "
                "time — one batch_bucket_plans call per packed "
                "batch inside run_batched (louvain/batched.py) — "
                "and coarse-phase re-planning belongs ON DEVICE "
                "(coarsen/rebin.py::device_rebin_plan, the "
                "sanctioned in-loop re-binner); hoist the host "
                "plan construction out of the loop, or justify "
                "with an inline '# graftlint: disable=R015'")


# ---------------------------------------------------------------------------
# R016: direct wall-clock reads in serve/ outside the injectable-clock
# plumbing (ISSUE 11).  Every deadline in the serving layer — linger,
# job deadline shedding, admission retry_after_s, retry backoff — runs
# on an injected ``clock`` so tests can drive it without sleeping.  A
# ``time.monotonic()`` / ``time.time()`` call added directly in serve/
# re-introduces the untestable-deadline trap: the behavior it gates can
# only be exercised by actually sleeping through it (slow, flaky), and
# a fake-clock test silently no longer covers the path.  The ONE
# sanctioned wall-clock site is serve/clock.py (the plumbing the
# injectable defaults come from); ``time.perf_counter()`` stays
# allowlisted everywhere — busy-window timing measures real elapsed
# work and is never compared against an injectable deadline.

_SERVE_CLOCK_MODULE = "cuvite_tpu/serve/clock.py"
# time.monotonic / time.time by dotted name, plus the bare from-import
# spelling of monotonic (a bare `time()` call is left out: it is far
# more likely to be a local callable than the stdlib clock).
_WALL_CLOCK_CALLS = {"time.monotonic", "time.time", "monotonic"}


@register
class ServeThreadingOutsideSeam(Rule):
    id = "R022"
    severity = "high"
    title = "threading primitive constructed directly in serve/ " \
            "outside the sync seam"

    # The seam module itself is the ONE sanctioned construction site.
    _SEAM = "cuvite_tpu/serve/sync.py"
    _PRIMS = ("Thread", "Lock", "RLock", "Event", "Condition",
              "Semaphore", "BoundedSemaphore", "Barrier")

    def check(self, sf):
        # R022 (ISSUE 14): every lock/event/thread the serving layer
        # creates must come from serve/sync.py's factories — a plain
        # threading.X in production AND a scheduler-backed twin under
        # the concheck cooperative scheduler (graftlint tier 4).  A
        # direct `threading.Lock()` in serve/ silently EXITS that
        # seam: the daemon still works, but concheck can no longer
        # serialize or replay schedules through the primitive, so the
        # exact race/deadlock classes tier 4 exists to catch go back
        # to reviewer vigilance.  PR 13 made the seam a convention;
        # this rule makes it a checked invariant.
        if not sf.rel.startswith(_SERVE_SCOPE) or sf.rel == self._SEAM:
            return
        aliases = {"threading"}
        bare: set = set()
        for node in sf.walk():
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "threading":
                        aliases.add(a.asname or "threading")
            elif isinstance(node, ast.ImportFrom) \
                    and node.module == "threading":
                for a in node.names:
                    if a.name in self._PRIMS:
                        bare.add(a.asname or a.name)
        for node in sf.walk():
            if not isinstance(node, ast.Call):
                continue
            fname = dotted(node.func)
            if fname is None:
                continue
            hit = None
            if "." in fname:
                mod, _, attr = fname.rpartition(".")
                if mod in aliases and attr in self._PRIMS:
                    hit = fname
            elif fname in bare:
                hit = fname
            if hit is None:
                continue
            yield self.finding(
                sf, node,
                f"{hit}() constructed directly in a serve/ module: "
                "serve/ synchronization primitives must come from the "
                "serve/sync.py factories (sync.Lock/RLock/Event/"
                "Condition/Thread) so the concheck cooperative "
                "scheduler (graftlint tier 4) can serialize, replay "
                "and race-check them; a raw threading primitive is "
                "invisible to every tier-4 schedule — use the seam, "
                "or justify with an inline '# graftlint: disable=R022'")


@register
class ServeWallClockOutsidePlumbing(Rule):
    id = "R016"
    severity = "high"
    title = "direct wall-clock read in serve/ outside the " \
            "injectable-clock plumbing"

    def check(self, sf):
        if not sf.rel.startswith(_SERVE_SCOPE) \
                or sf.rel == _SERVE_CLOCK_MODULE:
            return
        for node in sf.walk():
            if not isinstance(node, ast.Call):
                continue
            fname = dotted(node.func)
            if fname in _WALL_CLOCK_CALLS:
                yield self.finding(
                    sf, node,
                    f"{fname}() read directly in a serve/ module: "
                    "serving deadlines must run on the INJECTABLE "
                    "clock (serve/clock.py plumbing, threaded as the "
                    "clock=/sleep= parameters) or they become "
                    "untestable without real sleeps; call the injected "
                    "clock instead (time.perf_counter busy-timing is "
                    "allowlisted, and a reference like "
                    "clock=time.monotonic as a DEFAULT is fine — only "
                    "direct calls are flagged)")


# ---------------------------------------------------------------------------
# R029: resident-slab mutation outside the apply_delta_slab chokepoint
# (ISSUE 17).  A StreamSession keeps its slab (src/dst/w) RESIDENT on
# device between delta batches, and the serving pool hands the same
# arrays to every subsequent request — so those buffers are live
# references, not scratch.  The streaming contract routes every edit
# through ONE jitted chokepoint, stream/delta.py::apply_delta_slab
# (sentinel-retire + masked append + re-coalesce, pow2 class
# preserved), with grow_slab/shrink_slab as the only sanctioned class
# reshapes.  An ``x.at[...].set(...)`` written directly in stream/ or
# serve/ re-edits the slab OUTSIDE that seam: it silently forks the
# canonical form the bit-equality tests pin (ordering, padding
# sentinels, the 2m fixup), and under donation
# (``jit(..., donate_argnums=...)``) it is the donor-buffer aliasing
# trap outright — the resident reference the pool still holds now
# points at a donated (invalidated) buffer, which jax surfaces as a
# delete-buffer error only on the NEXT request that touches the
# tenant.  Both spellings are flagged; delta.py itself (the chokepoint)
# is exempt by path.

_STREAM_SLAB_SCOPE = (
    "cuvite_tpu/stream/",
    "cuvite_tpu/serve/",
)
_STREAM_SLAB_CHOKEPOINT = "cuvite_tpu/stream/delta.py"
# .at[...] update methods (jax.numpy.ndarray.at): every one writes.
_AT_UPDATE_METHODS = {
    "set", "add", "subtract", "multiply", "mul", "divide", "div",
    "power", "min", "max", "apply",
}


def _is_at_indexed_update(node: ast.Call) -> bool:
    """Matches ``<expr>.at[<idx>].<method>(...)`` — the functional
    index-update spelling, which on a RESIDENT buffer is still a slab
    edit even though it returns a copy."""
    f = node.func
    return (isinstance(f, ast.Attribute)
            and f.attr in _AT_UPDATE_METHODS
            and isinstance(f.value, ast.Subscript)
            and isinstance(f.value.value, ast.Attribute)
            and f.value.value.attr == "at")


@register
class ResidentSlabMutationOutsideChokepoint(Rule):
    id = "R029"
    severity = "high"
    title = "resident-slab mutation in stream//serve/ outside the " \
            "apply_delta_slab chokepoint (donor-buffer aliasing trap)"

    def check(self, sf):
        if not sf.rel.startswith(_STREAM_SLAB_SCOPE) \
                or sf.rel == _STREAM_SLAB_CHOKEPOINT:
            return
        for node in sf.walk():
            if not isinstance(node, ast.Call):
                continue
            if _is_at_indexed_update(node):
                yield self.finding(
                    sf, node,
                    f".at[...].{node.func.attr}() in a stream//serve/ "
                    "module: resident slabs are edited ONLY through "
                    "stream/delta.py::apply_delta_slab (sentinel-retire "
                    "+ masked append + re-coalesce, one jitted "
                    "chokepoint) so the canonical form the delta-vs-"
                    "rebuild bit-equality tests pin cannot fork; route "
                    "the edit through the chokepoint, or justify a "
                    "genuinely non-slab update with an inline "
                    "'# graftlint: disable=R029'")
                continue
            fname = dotted(node.func)
            if fname in _JIT_NAMES:
                for kw in node.keywords:
                    if kw.arg in ("donate_argnums", "donate_argnames"):
                        yield self.finding(
                            sf, kw.value,
                            f"jit({kw.arg}=...) in a stream//serve/ "
                            "module: donating a RESIDENT buffer "
                            "invalidates the reference the stream pool "
                            "still holds — the next request on the "
                            "tenant reads a deleted buffer; resident "
                            "slabs flow through apply_delta_slab "
                            "without donation, or justify with an "
                            "inline '# graftlint: disable=R029'")
