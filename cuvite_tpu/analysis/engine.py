"""graftlint engine: source model, rule registry, suppressions, baseline.

The engine is deliberately self-contained (stdlib ``ast`` only — no jax
import, no third-party dependency) so it can run in any environment the
repo runs in, including bare CI containers, in well under a second for
the whole tree.

Per-file model (``SourceFile``)
-------------------------------
Each analysed file is parsed once and annotated with the facts every
rule needs:

  * a parent map (``ast`` has no parent pointers), so rules can walk
    *up* from a call site through its enclosing ``if``/``try`` blocks;
  * the function table: every ``def`` (nested included) with its
    parameters, decorators, and module-local call edges;
  * jit roots: functions decorated with ``@jax.jit`` (bare or via
    ``functools.partial``) or wrapped at a call site (``jax.jit(f)`` /
    ``jax.jit(f, static_argnames=...)``), with their static argument
    names resolved from ``static_argnums``/``static_argnames``;
  * the jit-*reachable* closure: jit roots plus every same-module
    function transitively called from one.  Cross-module reachability is
    intentionally out of scope — name-based linking across imports would
    trade a bounded false-negative rate for an unbounded false-positive
    rate (see ANALYSIS.md, "Scope & limits").

Suppressions
------------
``# graftlint: disable=R001`` (comma-separated ids, or ``all``) on the
flagged line suppresses findings on that line only.
``# graftlint: disable-file=R003`` within the first ``FILE_PRAGMA_LINES``
lines suppresses a rule for the whole file.

Baseline
--------
A checked-in JSON file grandfathers pre-existing findings so the gate
only bites on *new* ones.  Entries are matched as a multiset of
``(path, rule, stripped-source-line)`` fingerprints — line *numbers* are
deliberately excluded so unrelated edits above a grandfathered finding
do not invalidate the baseline.
"""

from __future__ import annotations

import ast
import collections
import dataclasses
import json
import os
import re
from typing import Iterable, Iterator

SEVERITIES = ("high", "medium", "low")

FILE_PRAGMA_LINES = 20

_SUPPRESS_RE = re.compile(r"#\s*graftlint:\s*disable=([A-Za-z0-9_,\s]+)")
_FILE_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable-file=([A-Za-z0-9_,\s]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""

    rule: str
    severity: str
    path: str
    line: int
    message: str
    snippet: str  # stripped source line: the baseline fingerprint

    def fingerprint(self) -> tuple:
        return (self.path, self.rule, self.snippet)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def format(self) -> str:
        return (f"{self.path}:{self.line}: {self.rule} "
                f"[{self.severity}] {self.message}")


class Rule:
    """Base class for graftlint rules.

    Subclasses set ``id`` (``R###``), ``severity`` (one of SEVERITIES),
    ``title``, and implement ``check`` yielding raw findings — the
    engine applies suppressions and the baseline afterwards.
    """

    id: str = ""
    severity: str = "medium"
    title: str = ""

    def check(self, sf: "SourceFile") -> Iterable[Finding]:
        raise NotImplementedError

    def finding(self, sf: "SourceFile", node: ast.AST,
                message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(rule=self.id, severity=self.severity, path=sf.rel,
                       line=line, message=message, snippet=sf.line(line))


_REGISTRY: dict[str, Rule] = {}


def register(cls):
    """Class decorator adding a rule (one shared instance) to the
    registry; idempotent per id so test re-imports don't duplicate."""
    inst = cls()
    if not inst.id or inst.severity not in SEVERITIES:
        raise ValueError(f"rule {cls.__name__}: bad id/severity")
    _REGISTRY[inst.id] = inst
    return cls


def all_rules() -> list:
    return [_REGISTRY[k] for k in sorted(_REGISTRY)]


def dotted(node: ast.AST) -> str | None:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _const_names(node: ast.AST) -> list | None:
    """String constant or tuple/list of string constants -> list of str."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            if not (isinstance(el, ast.Constant)
                    and isinstance(el.value, str)):
                return None
            out.append(el.value)
        return out
    return None


def _const_ints(node: ast.AST) -> list | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            if not (isinstance(el, ast.Constant)
                    and isinstance(el.value, int)):
                return None
            out.append(el.value)
        return out
    return None


_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}
_PARTIAL_NAMES = {"functools.partial", "partial"}


def _jit_call(node: ast.AST) -> ast.Call | None:
    """The Call node if ``node`` is ``jax.jit(...)`` or
    ``functools.partial(jax.jit, ...)``; else None."""
    if not isinstance(node, ast.Call):
        return None
    name = dotted(node.func)
    if name in _JIT_NAMES:
        return node
    if name in _PARTIAL_NAMES and node.args \
            and dotted(node.args[0]) in _JIT_NAMES:
        return node
    return None


@dataclasses.dataclass
class FunctionInfo:
    name: str
    node: ast.AST                      # FunctionDef / AsyncFunctionDef
    params: list                       # positional+kw-only param names
    is_jit: bool = False               # decorated / wrapped with jax.jit
    static_names: set = dataclasses.field(default_factory=set)
    calls: set = dataclasses.field(default_factory=set)  # local callee names
    jit_reachable: bool = False


def _params_of(node) -> list:
    a = node.args
    names = [p.arg for p in a.posonlyargs + a.args]
    kwonly = [p.arg for p in a.kwonlyargs]
    return names + kwonly


def _statics_from_jit_call(call: ast.Call, params: list) -> set:
    """Resolve static_argnums/static_argnames keywords of a jit (or
    partial-of-jit) call against a parameter list."""
    statics: set = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            names = _const_names(kw.value)
            if names:
                statics.update(names)
        elif kw.arg == "static_argnums":
            nums = _const_ints(kw.value)
            if nums:
                pos = [p for p in params]
                for i in nums:
                    if 0 <= i < len(pos):
                        statics.add(pos[i])
    return statics


class _Builder(ast.NodeVisitor):
    """Single pass collecting parents, the function table, per-function
    call edges, and call-site jit wraps (``jax.jit(f)``)."""

    def __init__(self, sf: "SourceFile"):
        self.sf = sf
        self.stack: list[FunctionInfo] = []

    def generic_visit(self, node):
        for child in ast.iter_child_nodes(node):
            self.sf.parent_map[child] = node
            self.visit(child)

    def _visit_funcdef(self, node):
        info = FunctionInfo(name=node.name, node=node,
                            params=_params_of(node))
        for dec in node.decorator_list:
            if dotted(dec) in _JIT_NAMES:
                info.is_jit = True
            else:
                call = _jit_call(dec)
                if call is not None:
                    info.is_jit = True
                    info.static_names |= _statics_from_jit_call(
                        call, info.params)
        self.sf.functions.append(info)
        self.sf.func_by_name[node.name].append(info)
        self.sf.func_of_node[node] = info
        self.stack.append(info)
        self.generic_visit(node)
        self.stack.pop()

    visit_FunctionDef = _visit_funcdef
    visit_AsyncFunctionDef = _visit_funcdef

    def visit_Call(self, node):
        if self.stack:
            if isinstance(node.func, ast.Name):
                self.stack[-1].calls.add(node.func.id)
            elif isinstance(node.func, ast.Attribute):
                self.stack[-1].calls.add(node.func.attr)
        # Call-site wrap: jax.jit(f[, static_argnames=...]) marks local f
        # as a jit root (the `return jax.jit(step)` factory idiom).
        if dotted(node.func) in _JIT_NAMES and node.args:
            target = node.args[0]
            tname = target.id if isinstance(target, ast.Name) else None
            if tname:
                self.sf.jit_wrapped[tname] = node
        self.generic_visit(node)


class SourceFile:
    """Parsed + annotated source file (see module docstring)."""

    def __init__(self, text: str, path: str = "<string>",
                 rel: str | None = None):
        self.path = path
        self.rel = rel if rel is not None else path
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        self.parent_map: dict = {}
        self.functions: list[FunctionInfo] = []
        self.func_by_name: dict = collections.defaultdict(list)
        self.func_of_node: dict = {}
        self.jit_wrapped: dict = {}
        _Builder(self).visit(self.tree)
        self._apply_jit_wraps()
        self._propagate_reachability()
        self._line_suppress, self._file_suppress = self._parse_suppressions()

    # -- construction helpers ------------------------------------------

    def _apply_jit_wraps(self):
        for name, call in self.jit_wrapped.items():
            infos = self.func_by_name.get(name, ())
            for info in infos:
                info.is_jit = True
                # Statics only attach when the name is unambiguous: with
                # several same-named factory-locals (bucketed.py defines
                # 'step' three times) the wrap cannot be attributed, and
                # wrongly marking a traced param static would silently
                # blind R002's traced-branch check for the others.
                if len(infos) == 1:
                    info.static_names |= _statics_from_jit_call(
                        call, info.params)

    def _propagate_reachability(self):
        queue = [f for f in self.functions if f.is_jit]
        for f in queue:
            f.jit_reachable = True
        while queue:
            f = queue.pop()
            for callee in f.calls:
                for g in self.func_by_name.get(callee, ()):
                    if not g.jit_reachable:
                        g.jit_reachable = True
                        queue.append(g)

    def _parse_suppressions(self):
        """Pragmas are read from real COMMENT tokens, not raw line text:
        a docstring QUOTING the suppression syntax (ANALYSIS.md does!)
        must not silently disable rules for the file containing it."""
        line_sup: dict = {}
        file_sup: set = set()
        for lineno, comment in self._iter_comments():
            if "graftlint" not in comment:
                continue
            m = _SUPPRESS_RE.search(comment)
            if m:
                ids = {s.strip() for s in m.group(1).split(",") if s.strip()}
                line_sup.setdefault(lineno, set()).update(ids)
            m = _FILE_SUPPRESS_RE.search(comment)
            if m and lineno <= FILE_PRAGMA_LINES:
                file_sup |= {s.strip() for s in m.group(1).split(",")
                             if s.strip()}
        return line_sup, file_sup

    def _iter_comments(self):
        """(lineno, text) of every comment token.  Falls back to a raw
        line scan if tokenize rejects what ast accepted (not expected —
        but losing suppressions wholesale would flip every suppressed
        intentional finding back into a gate failure)."""
        import io
        import tokenize

        try:
            toks = list(tokenize.generate_tokens(
                io.StringIO(self.text).readline))
        except (tokenize.TokenError, IndentationError, SyntaxError):
            return [(i, raw) for i, raw in enumerate(self.lines, start=1)
                    if "#" in raw]
        return [(t.start[0], t.string) for t in toks
                if t.type == tokenize.COMMENT]

    # -- rule-facing API -----------------------------------------------

    def line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def suppressed(self, lineno: int, rule_id: str) -> bool:
        if rule_id in self._file_suppress or "all" in self._file_suppress:
            return True
        ids = self._line_suppress.get(lineno, ())
        return rule_id in ids or "all" in ids

    def parent(self, node: ast.AST) -> ast.AST | None:
        return self.parent_map.get(node)

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self.parent_map.get(node)
        while cur is not None:
            yield cur
            cur = self.parent_map.get(cur)

    def enclosing_function(self, node: ast.AST) -> FunctionInfo | None:
        for anc in self.ancestors(node):
            info = self.func_of_node.get(anc)
            if info is not None:
                return info
        return None

    def walk(self):
        return ast.walk(self.tree)


# ---------------------------------------------------------------------------
# Running


def _severity_rank(sev: str) -> int:
    return SEVERITIES.index(sev)


def run_source(text: str, path: str = "<string>", rules=None,
               rel: str | None = None, *,
               sf: "SourceFile | None" = None) -> list:
    """Lint one source string; returns suppression-filtered findings.

    The unit-test entry point: rules see exactly what they would see for
    a real file at ``rel``/``path``.  ``sf`` lets run_paths pass the
    SourceFile it already built (it needs one for the tier-2 summary) —
    the check/suppress/sort semantics then live HERE, once, for both
    entry points."""
    if rules is None:
        rules = all_rules()
    if sf is None:
        sf = SourceFile(text, path=path, rel=rel)
    out = []
    for rule in rules:
        for f in rule.check(sf):
            if not sf.suppressed(f.line, f.rule):
                out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


def iter_py_files(paths: Iterable[str]) -> Iterator[str]:
    """All .py files under the given files/directories, sorted, deduped."""
    seen = set()
    for p in paths:
        if os.path.isfile(p):
            # An explicit non-.py argument is not linted as Python: the
            # caller gets the 'no Python files' E000 from run_paths
            # instead of a bogus syntax-error finding on a shell script.
            files = [p] if p.endswith(".py") else []
        else:
            files = []
            for root, dirs, names in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                files.extend(os.path.join(root, n) for n in sorted(names)
                             if n.endswith(".py"))
        for f in files:
            key = os.path.abspath(f)
            if key not in seen:
                seen.add(key)
                yield f


# Path-scoped rules (R003/R006/R007/R008) and baseline fingerprints key
# on repo-root-relative paths, so rel must be anchored to the REPO ROOT,
# not the CWD — otherwise linting from one directory up would rewrite
# every rel to 'repo/tools/...', silently disabling the scoped rules and
# unmatching the whole baseline while still printing 'ok'.
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _relpath(path: str, anchor: str | None = None) -> str:
    """Repo-root-relative when inside the repo; else relative to
    ``anchor`` (the parent of the scan-root argument, so an external
    '<tree>/tools' exercises the tools/-scoped rules REGARDLESS of the
    CWD — the anchor must outrank the CWD, or linting that tree from an
    ancestor directory would resolve 'rpt/ext/tools/...' and silently
    skip every scoped rule); CWD-relative as the last resort."""
    ap = os.path.abspath(path)
    for base in (_REPO_ROOT, anchor, os.getcwd()):
        if base is None:
            continue
        rel = os.path.relpath(ap, base)
        if not rel.startswith(".."):
            return rel.replace(os.sep, "/")
    return ap.replace(os.sep, "/")


def _collect_files(paths: Iterable[str]):
    """([(abs file, anchor)], [E000 findings for barren inputs]) — the
    shared traversal of run_paths and linted_rels, so what counts as
    'linted' cannot drift between the gate and the baseline-hygiene
    scoping built on it."""
    files, errors = [], []
    for p in paths:
        batch = list(iter_py_files([p]))
        if not batch:
            errors.append(Finding(
                rule="E000", severity="high", path=str(p), line=1,
                message="path contains no Python files (missing or "
                        "renamed? the gate would silently pass)",
                snippet=""))
        # Anchor = parent of the SCAN ROOT: for a file argument that is
        # the file's grandparent dir, so 'lint /ext/tools/bench.py' and
        # 'lint /ext/tools' both resolve rel='tools/bench.py' and hit
        # the same scoped rules.
        anchor = os.path.dirname(os.path.abspath(p))
        if os.path.isfile(p):
            anchor = os.path.dirname(anchor)
        files.extend((f, anchor) for f in batch)
    return files, errors


def linted_rels(paths: Iterable[str]) -> set:
    """The repo-relative paths a run_paths(paths) call would lint — the
    scope guard for baseline hygiene: staleness and pruning must only
    ever judge entries whose file was actually (re)checked."""
    files, _errors = _collect_files(paths)
    return {_relpath(f, anchor) for f, anchor in files}


def run_paths(paths: Iterable[str], rules=None, *, project: bool = True,
              cache: str | None = None) -> list:
    """Lint every .py file under ``paths``.  Failure is CLOSED on both
    bad inputs: an unparsable file yields a high-severity E000 finding
    instead of aborting the run, and an input path with no Python files
    under it (typo, renamed directory) yields one too — otherwise a
    stale CI invocation would print 'ok' forever while linting
    nothing.

    ``project=True`` (default) additionally runs the tier-2
    cross-module pass (analysis/callgraph.py: R017/R018) over the whole
    file set.  ``cache`` names an incremental-cache JSON file
    (analysis/cache.py): per-file findings and tier-2 summaries are
    reused for files whose content hash matches, bit-identically to a
    cold run.  The cache only engages with the full default rule set —
    a narrowed ``rules`` list always lints cold, so cached entries can
    never leak findings the caller did not ask for (or hide ones they
    did)."""
    from cuvite_tpu.analysis import callgraph
    from cuvite_tpu.analysis.cache import LintCache, content_sha

    cache_obj = LintCache(cache) if cache and rules is None else None
    if rules is None:
        rules = all_rules()
    files, findings = _collect_files(paths)
    summaries = []
    seen = set()
    for fpath, anchor in files:
        if os.path.abspath(fpath) in seen:
            continue
        seen.add(os.path.abspath(fpath))
        rel = _relpath(fpath, anchor)
        try:
            with open(fpath, encoding="utf-8") as fh:
                text = fh.read()
        except (OSError, UnicodeDecodeError) as e:
            findings.append(Finding(
                rule="E000", severity="high", path=rel, line=1,
                message=f"cannot read file: {e}", snippet=""))
            continue
        if cache_obj is not None:
            sha = content_sha(text)
            hit = cache_obj.get(rel, sha)
            if hit is not None:
                cached, summary = hit
                findings.extend(Finding(**d) for d in cached)
                if summary is not None:
                    summaries.append(summary)
                continue
        try:
            sf = SourceFile(text, path=fpath, rel=rel)
        except SyntaxError as e:
            findings.append(Finding(
                rule="E000", severity="high", path=rel,
                line=e.lineno or 1,
                message=f"syntax error: {e.msg}", snippet=""))
            continue
        except ValueError as e:
            # e.g. ast.parse on a null byte: not a SyntaxError, but the
            # same fail-closed answer
            findings.append(Finding(
                rule="E000", severity="high", path=rel, line=1,
                message=f"unparsable source: {e}", snippet=""))
            continue
        per_file = run_source(text, path=fpath, rules=rules, rel=rel,
                              sf=sf)
        summary = None
        if project or cache_obj is not None:
            summary = callgraph.summarize(sf)
            summaries.append(summary)
        findings.extend(per_file)
        if cache_obj is not None:
            cache_obj.put(rel, sha, per_file, summary)
    if project:
        findings.extend(callgraph.run_project(summaries, rules=rules))
    if cache_obj is not None:
        cache_obj.save()
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


# ---------------------------------------------------------------------------
# Baseline

BASELINE_VERSION = 1


def load_baseline(path: str) -> collections.Counter:
    """Baseline file -> Counter of (path, rule, snippet) fingerprints.
    A missing file is an empty baseline (first-run ergonomics)."""
    if not os.path.exists(path):
        return collections.Counter()
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path!r}: unsupported version {data.get('version')!r}")
    counter: collections.Counter = collections.Counter()
    for ent in data.get("findings", []):
        key = (ent["path"], ent["rule"], ent["snippet"])
        counter[key] += int(ent.get("count", 1))
    return counter


def write_baseline(path: str, findings: Iterable[Finding]) -> None:
    # E000 (unreadable/unparsable file) is deliberately NOT baselineable:
    # its fingerprint carries no snippet, so one grandfathered parse
    # error would match every FUTURE parse error of that path — i.e.
    # permanently un-lint the file.  Infrastructure errors must always
    # fail the gate.
    counter: collections.Counter = collections.Counter(
        f.fingerprint() for f in findings if f.rule != "E000")
    ents = [
        {"path": p, "rule": r, "snippet": s, "count": c}
        for (p, r, s), c in sorted(counter.items())
    ]
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": BASELINE_VERSION, "findings": ents}, fh,
                  indent=2, sort_keys=True)
        fh.write("\n")


def apply_baseline(findings: list, baseline: collections.Counter):
    """Split findings into (new, grandfathered) against the baseline
    multiset.  Duplicate fingerprints consume baseline slots in source
    order, so N baselined copies admit exactly N occurrences."""
    budget = collections.Counter(baseline)
    new, old = [], []
    for f in findings:
        key = f.fingerprint()
        # E000 never matches the baseline, even a hand-edited one — see
        # write_baseline.
        if f.rule != "E000" and budget[key] > 0:
            budget[key] -= 1
            old.append(f)
        else:
            new.append(f)
    return new, old


def stale_baseline_entries(findings: list, baseline: collections.Counter,
                           linted: set | None = None) -> list:
    """Baseline slots no CURRENT finding consumes: [(fingerprint,
    n_unmatched)].  Each dead slot silently admits one future regression
    at the same (path, rule, snippet) — the hygiene report surfaces
    them and ``--prune-baseline`` deletes them.

    ``linted`` (a set of repo-relative paths, see :func:`linted_rels`)
    scopes the judgment: an entry for a file this run did NOT lint is
    unknown, not stale — without the scope, a subset run (``lint.sh
    --changed``, an explicit path argument) would report every other
    file's live grandfathered findings as dead."""
    have = collections.Counter(
        f.fingerprint() for f in findings if f.rule != "E000")
    out = []
    for key, n in sorted(baseline.items()):
        if linted is not None and key[0] not in linted:
            continue
        extra = n - have.get(key, 0)
        if extra > 0:
            out.append((key, extra))
    return out


def prune_baseline(path: str, findings: list,
                   linted: set | None = None) -> int:
    """Rewrite the baseline at ``path`` keeping, per fingerprint, only
    as many slots as current findings consume; returns the number of
    dead slots dropped.  A no-op (0) when the file is already tight.
    ``linted`` scopes exactly like :func:`stale_baseline_entries`:
    entries for files outside the linted set are KEPT untouched —
    pruning from a subset run must never delete another file's live
    grandfathered slots."""
    baseline = load_baseline(path)
    have = collections.Counter(
        f.fingerprint() for f in findings if f.rule != "E000")
    kept: collections.Counter = collections.Counter()
    dropped = 0
    for key, n in baseline.items():
        if linted is not None and key[0] not in linted:
            kept[key] = n
            continue
        keep = min(n, have.get(key, 0))
        if keep:
            kept[key] = keep
        dropped += n - keep
    if dropped:
        ents = [
            {"path": p, "rule": r, "snippet": s, "count": c}
            for (p, r, s), c in sorted(kept.items())
        ]
        with open(path, "w", encoding="utf-8") as fh:
            json.dump({"version": BASELINE_VERSION, "findings": ents}, fh,
                      indent=2, sort_keys=True)
            fh.write("\n")
    return dropped


def gate_failures(findings: list, min_severity: str = "high") -> list:
    """The findings that fail the gate: severity at or above
    ``min_severity`` (after baseline filtering by the caller)."""
    cut = _severity_rank(min_severity)
    return [f for f in findings if _severity_rank(f.severity) <= cut]
