"""Tier 4 (static half) — lock-order and atomicity rules for serve/.

Before the dispatcher goes multi-threaded (ROADMAP 1b: double-buffered
dispatch), the analyzer must see the two concurrency hazard classes
R019's lockset inference cannot: *ordering* (two locks acquired in
opposite orders on two paths — deadlock potential that no single-file,
single-field view can express) and *atomicity* (a guarded-field read
outside the lock deciding a mutation made under it — the check-then-act
shape PR 11 hand-audited in the drain/duplicate-id paths).

**R020 — lock-order cycle** (project tier).  Every serve/ file reduces
to a :func:`lock_summary`: per class, the attr→class map its
constructor proves (``self.stats = ServeStats()``, ``self.server =
server`` with a ``server: LouvainServer`` annotation), which lock
attributes are reentrant (an ``RLock`` spelling in their declaration),
and per method the lock acquisitions, the lexically nested
acquisitions, the calls made while holding a lock, and the resolvable
calls overall.  The project pass links the summaries: lock expressions
normalize to ``OwnerClass.attr`` by walking the attr→class maps
(``self.stats.lock`` in LouvainServer → ``ServeStats.lock``), call
targets resolve the same way (param annotations and ``x = self.attr``
local aliases included), and an **acquisition graph** forms — an edge
``A → B`` wherever a thread can hold ``A`` while acquiring ``B``,
either lexically nested or through a resolved call chain.  A cycle is
a potential deadlock; a self-edge on a provably non-reentrant ``Lock``
is a guaranteed one.  Summaries are plain JSON and ride the
incremental lint cache exactly like the tier-2 dataflow summaries —
the *dynamic* half of tier 4 (analysis/concheck.py) is never cached.

**R021 — check-then-act outside the lock** (per file).  A read of an
R019-guarded field inside an ``if``/``while`` test NOT holding the
guard, in a function that also mutates that field UNDER the guard: the
decision can go stale between the test and the mutation.  The fix is
the drain-recheck idiom daemon._handle_submit uses — take the lock,
re-check, then act.

Both rules scope to ``cuvite_tpu/serve/`` (the only concurrent
package) and resolve only what imports/annotations/constructors prove
— unresolvable receivers contribute no edges (bounded false negatives,
near-zero false positives; the house contract).
"""

from __future__ import annotations

import ast

from cuvite_tpu.analysis.callgraph import ProjectRule
from cuvite_tpu.analysis.engine import Rule, dotted, register
from cuvite_tpu.analysis.lockset import (
    LOCKSET_SCOPE,
    _annotations,
    _ClassFacts,
    _lock_of_with_item,
)

LOCK_SUMMARY_VERSION = 1


def _annotation_names(node: ast.AST | None) -> list:
    """Class names an annotation can prove: ``B``, ``"B"``,
    ``B | None``, ``Optional[B]``."""
    if node is None:
        return []
    if isinstance(node, ast.Name):
        return [node.id]
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        # 'B' / 'B | None' forward references
        return [p.strip() for p in node.value.split("|")
                if p.strip() and p.strip() != "None"]
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.BitOr):
        return _annotation_names(node.left) + _annotation_names(node.right)
    if isinstance(node, ast.Subscript):    # Optional[B] / Union[B, None]
        out = []
        sl = node.slice
        for el in (sl.elts if isinstance(sl, ast.Tuple) else [sl]):
            out.extend(_annotation_names(el))
        return out
    return []


def _class_attr_map(cls: ast.ClassDef) -> tuple:
    """(attrs, reentrant): ``attrs`` maps instance attribute -> the
    class name its constructor provably binds; ``reentrant`` is the set
    of own lock attrs whose declaration spells RLock."""
    attrs: dict = {}
    reentrant: set = set()
    # class-body declarations (dataclass fields): reentrancy only
    for stmt in cls.body:
        if isinstance(stmt, ast.AnnAssign) \
                and isinstance(stmt.target, ast.Name):
            src = ast.unparse(stmt)
            if "lock" in stmt.target.id.lower() and "RLock" in src:
                reentrant.add(stmt.target.id)
    for fn in cls.body:
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                or fn.name not in ("__init__", "__post_init__"):
            continue
        ann = {a.arg: _annotation_names(a.annotation)
               for a in fn.args.args + fn.args.kwonlyargs}
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            tgt = node.targets[0]
            if not (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"):
                continue
            val = node.value
            if isinstance(val, ast.Call):
                callee = dotted(val.func)
                if callee:
                    last = callee.split(".")[-1]
                    attrs.setdefault(tgt.attr, last)
                    if "lock" in tgt.attr.lower() and "RLock" in callee:
                        reentrant.add(tgt.attr)
            elif isinstance(val, ast.Name) and val.id in ann:
                for name in ann[val.id]:
                    attrs.setdefault(tgt.attr, name)
                    break
    return attrs, reentrant


def _local_aliases(fn: ast.AST) -> dict:
    """name -> ('attr', 'a.b') for ``x = self.a.b`` assignments and
    ('cls', 'C') for annotated params — the receivers a method call can
    resolve through."""
    out: dict = {}
    args = fn.args
    for a in args.args + args.kwonlyargs + args.posonlyargs:
        names = _annotation_names(a.annotation)
        if names:
            out[a.arg] = ("cls", names[0])
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            name = dotted(node.value)
            if name and name.startswith("self."):
                out[node.targets[0].id] = ("attr", name[len("self."):])
    return out


def _method_summary(sf, cls: ast.ClassDef, fn) -> dict:
    """Acquisitions, nested acquisition edges, calls-under-lock, and
    all dotted calls of one method (raw expressions; the project pass
    normalizes)."""
    held: dict = {}     # node id -> list of lock exprs held (outer first)
    acquires: list = []
    nested: list = []
    # ast.walk visits an enclosing With before any nested one, so by
    # the time a With is processed its descendants already carry the
    # outer locks — extending with THIS With's locks keeps the held
    # list in acquisition order.
    for node in ast.walk(fn):
        if not isinstance(node, ast.With):
            continue
        outer = held.get(id(node), [])
        exprs = []
        for item in node.items:
            hit = _lock_of_with_item(item.context_expr)
            if hit is not None:
                exprs.append(hit[0])
        if not exprs:
            continue
        line = node.lineno
        for i, expr in enumerate(exprs):
            acquires.append({"lock": expr, "line": line,
                             "snippet": sf.line(line)})
            for o in outer + exprs[:i]:
                nested.append({"outer": o, "inner": expr, "line": line,
                               "snippet": sf.line(line)})
        for inner in ast.walk(node):
            if inner is node:
                continue
            held.setdefault(id(inner), []).extend(exprs)
    calls: list = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        callee = dotted(node.func)
        if not callee:
            continue
        calls.append({"callee": callee, "line": node.lineno,
                      "snippet": sf.line(node.lineno),
                      "under": list(dict.fromkeys(
                          held.get(id(node), [])))})
    return {"acquires": acquires, "nested": nested, "calls": calls,
            "aliases": {k: list(v) for k, v in _local_aliases(fn).items()}}


def lock_summary(sf) -> dict | None:
    """The file's lock-acquisition facts as plain JSON (None outside
    serve/ — the only concurrent package; elsewhere the summary would
    be dead weight in the cache)."""
    if not sf.rel.startswith(LOCKSET_SCOPE):
        return None
    classes: dict = {}
    for cls in sf.walk():
        if not isinstance(cls, ast.ClassDef):
            continue
        attrs, reentrant = _class_attr_map(cls)
        methods: dict = {}
        for fn in cls.body:
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                methods[fn.name] = _method_summary(sf, cls, fn)
        classes[cls.name] = {
            "attrs": attrs,
            "reentrant": sorted(reentrant),
            "methods": methods,
        }
    return {"version": LOCK_SUMMARY_VERSION, "rel": sf.rel,
            "classes": classes}


# ---------------------------------------------------------------------------
# R020 — the project-tier acquisition graph


class _LockGraph:
    """Links per-file lock summaries into one acquisition graph."""

    def __init__(self, summaries):
        self.classes: dict = {}     # class name -> (rel, class summary)
        for s in summaries:
            locks = (s or {}).get("locks") or {}
            if locks.get("version") != LOCK_SUMMARY_VERSION:
                continue
            for cname, cdata in locks.get("classes", {}).items():
                self.classes[cname] = (locks["rel"], cdata)
        # edges: (outer, inner) -> first site {"rel", "line", "snippet",
        # "via"} — deterministic: summaries arrive in sorted-rel order.
        self.edges: dict = {}
        self._locks_in_cache: dict = {}
        self._build()

    # -- normalization -------------------------------------------------

    def _attr_class(self, cls: str, attr: str) -> str | None:
        ent = self.classes.get(cls)
        if ent is None:
            return None
        tgt = ent[1]["attrs"].get(attr)
        return tgt if tgt in self.classes else None

    def _walk_attrs(self, cls: str, parts: list) -> str | None:
        """Resolve an attribute chain of classes: cls, a, b -> class of
        ``self.a.b`` (None when any hop is unproven)."""
        cur = cls
        for p in parts:
            cur = self._attr_class(cur, p)
            if cur is None:
                return None
        return cur

    def normalize_lock(self, cls: str, expr: str,
                       aliases: dict | None = None) -> str | None:
        """'self.stats.lock' in LouvainServer -> 'ServeStats.lock';
        'client.wlock' with a ``client: _Client`` annotation ->
        '_Client.wlock'.  None when the owner cannot be proven."""
        parts = expr.split(".")
        if parts[0] == "self":
            owner = self._walk_attrs(cls, parts[1:-1])
            return f"{owner}.{parts[-1]}" if owner else None
        alias = (aliases or {}).get(parts[0])
        if alias is None:
            return None
        kind, val = alias
        base = (self._walk_attrs(cls, val.split("."))
                if kind == "attr" else
                (val if val in self.classes else None))
        if base is None:
            return None
        owner = self._walk_attrs(base, parts[1:-1])
        return f"{owner}.{parts[-1]}" if owner else None

    def resolve_call(self, cls: str, callee: str,
                     aliases: dict | None = None) -> tuple | None:
        """'self.server.submit' -> ('LouvainServer', 'submit') when the
        chain is proven and the target class defines the method."""
        parts = callee.split(".")
        if len(parts) < 2:
            return None
        if parts[0] == "self":
            owner = self._walk_attrs(cls, parts[1:-1])
        else:
            alias = (aliases or {}).get(parts[0])
            if alias is None:
                return None
            kind, val = alias
            base = (self._walk_attrs(cls, val.split("."))
                    if kind == "attr" else
                    (val if val in self.classes else None))
            if base is None:
                return None
            owner = self._walk_attrs(base, parts[1:-1])
        if owner is None:
            return None
        if parts[-1] not in self.classes[owner][1]["methods"]:
            return None
        return owner, parts[-1]

    # -- transitive lock closure ---------------------------------------

    def locks_in(self, cls: str, method: str, _seen=None) -> set:
        """Every normalized lock (cls, method) can acquire, directly or
        through resolved calls (cycle-safe, memoized)."""
        key = (cls, method)
        hit = self._locks_in_cache.get(key)
        if hit is not None:
            return hit
        seen = _seen if _seen is not None else set()
        if key in seen:
            return set()
        seen.add(key)
        m = self.classes[cls][1]["methods"][method]
        aliases = m.get("aliases", {})
        out: set = set()
        for acq in m["acquires"]:
            lk = self.normalize_lock(cls, acq["lock"], aliases)
            if lk:
                out.add(lk)
        for call in m["calls"]:
            tgt = self.resolve_call(cls, call["callee"], aliases)
            if tgt is not None:
                out |= self.locks_in(*tgt, _seen=seen)
        if _seen is None:       # memoize only fully-expanded closures
            self._locks_in_cache[key] = out
        return out

    # -- the graph ------------------------------------------------------

    def _add_edge(self, outer: str, inner: str, rel: str, line: int,
                  snippet: str, via: str) -> None:
        self.edges.setdefault((outer, inner), {
            "rel": rel, "line": line, "snippet": snippet, "via": via})

    def _build(self) -> None:
        for cname in sorted(self.classes):
            rel, cdata = self.classes[cname]
            for mname in sorted(cdata["methods"]):
                m = cdata["methods"][mname]
                aliases = m.get("aliases", {})
                for e in m["nested"]:
                    outer = self.normalize_lock(cname, e["outer"], aliases)
                    inner = self.normalize_lock(cname, e["inner"], aliases)
                    if outer and inner:
                        self._add_edge(outer, inner, rel, e["line"],
                                       e["snippet"],
                                       f"{cname}.{mname} (nested with)")
                for call in m["calls"]:
                    if not call["under"]:
                        continue
                    tgt = self.resolve_call(cname, call["callee"], aliases)
                    if tgt is None:
                        continue
                    inner_locks = self.locks_in(*tgt)
                    for outer_expr in call["under"]:
                        outer = self.normalize_lock(cname, outer_expr,
                                                    aliases)
                        if not outer:
                            continue
                        for inner in inner_locks:
                            self._add_edge(
                                outer, inner, rel, call["line"],
                                call["snippet"],
                                f"{cname}.{mname} -> "
                                f"{tgt[0]}.{tgt[1]}()")

    def is_reentrant(self, lock: str) -> bool:
        cls, _, attr = lock.rpartition(".")
        ent = self.classes.get(cls)
        return ent is not None and attr in ent[1]["reentrant"]

    def cycles(self) -> list:
        """Elementary cycles in the acquisition graph, canonicalized
        (rotation starting at the min lock) and deduplicated.  Self
        edges are returned as 1-cycles only for provably non-reentrant
        locks (re-entering an RLock is legal by construction)."""
        adj: dict = {}
        for (a, b) in self.edges:
            adj.setdefault(a, set()).add(b)
        out = []
        seen = set()
        for (a, b) in sorted(self.edges):
            if a == b:
                if not self.is_reentrant(a) and (a,) not in seen:
                    seen.add((a,))
                    out.append([a, a])
                continue
            # DFS from b back to a (bounded; the lock population is
            # tiny — a handful per package).
            stack = [(b, [a, b])]
            found = None
            visited = set()
            while stack and found is None:
                cur, path = stack.pop()
                if cur == a:
                    found = path
                    break
                if cur in visited or len(path) > 8:
                    continue
                visited.add(cur)
                for nxt in sorted(adj.get(cur, ())):
                    if nxt == a:
                        found = path + [a]
                        break
                    stack.append((nxt, path + [nxt]))
            if found:
                cyc = found[:-1]
                lo = cyc.index(min(cyc))
                canon = tuple(cyc[lo:] + cyc[:lo])
                if canon not in seen:
                    seen.add(canon)
                    out.append(list(canon) + [canon[0]])
        return out


@register
class LockOrderCycle(ProjectRule):
    id = "R020"
    severity = "high"
    title = "lock-acquisition cycle across serve/ classes (deadlock " \
            "potential)"

    def check_project(self, project):
        graph = _LockGraph(project.summaries)
        for cyc in graph.cycles():
            pairs = list(zip(cyc, cyc[1:]))
            site = graph.edges.get(pairs[0])
            if site is None:
                continue
            order = " -> ".join(cyc)
            vias = "; ".join(
                f"{a}->{b} at {graph.edges[(a, b)]['rel']}:"
                f"{graph.edges[(a, b)]['line']} "
                f"[{graph.edges[(a, b)]['via']}]"
                for a, b in pairs if (a, b) in graph.edges)
            if len(cyc) == 2 and cyc[0] == cyc[1]:
                msg = (f"non-reentrant lock {cyc[0]} can be re-acquired "
                       f"while already held ({vias}): guaranteed "
                       "self-deadlock; make it an RLock or restructure "
                       "the call so the lock is released first")
            else:
                msg = (f"lock-order cycle {order} ({vias}): two threads "
                       "taking these locks in opposite orders can "
                       "deadlock; pick one global order (document it) "
                       "or collapse the critical sections")
            yield self.project_finding(
                {"rel": site["rel"]},
                {"line": site["line"], "snippet": site["snippet"]},
                msg)


# ---------------------------------------------------------------------------
# R021 — check-then-act atomicity


@register
class CheckThenActOutsideLock(Rule):
    id = "R021"
    severity = "high"
    title = "guarded-field read outside the lock deciding a mutation " \
            "made under it (check-then-act, serve/)"

    def check(self, sf):
        if not sf.rel.startswith(LOCKSET_SCOPE):
            return
        annotations = _annotations(sf)
        for cls in sf.walk():
            if not isinstance(cls, ast.ClassDef):
                continue
            facts = _ClassFacts(sf, cls, annotations)
            if not facts.guards:
                continue
            for owner, field, node, held, func in facts.reads_in_test(sf):
                locks = facts.guards.get((owner, field))
                if not locks or held & locks:
                    continue
                if func is None:
                    continue
                mutated_under = [
                    m for m in facts.mutations
                    if (m[0], m[1]) == (owner, field) and (m[4] & locks)
                    and sf.enclosing_function(m[3]) is func]
                if not mutated_under:
                    continue
                want = " or ".join(sorted(locks))
                mline = mutated_under[0][3].lineno
                yield self.finding(
                    sf, node,
                    f"'{owner}.{field}' is read here WITHOUT {want} to "
                    f"decide a branch, but '{func.name}' mutates it "
                    f"under the lock (line {mline}): the decision can "
                    "go stale between the test and the mutation "
                    "(check-then-act — the drain/duplicate-id shape). "
                    "Take the lock and re-check inside it, or justify "
                    "with an inline disable")
