"""Tier 3 — jaxpr lint + compile-budget audit (the dynamic tier).

Tiers 1/2 read source; this tier inspects the PROGRAMS the source
builds, because two invariants the serving stack rests on are invisible
to any AST walk:

  * **jaxpr hygiene** — the traced per-phase programs must contain no
    64-bit ops (the 32-bit device contract, R003's runtime twin), no
    ``pure_callback``/``io_callback`` escapes (a host callback inside
    the phase loop is a hidden per-iteration sync), and no in-graph
    ``device_put`` transfers (placement belongs to the driver's one
    upload per batch).  :func:`lint_jaxpr` walks a ClosedJaxpr
    (sub-jaxprs included) and reports J001/J002/J003 findings.

  * **compile budget** — "batch content never enters the compile key"
    (PR 10's measured contract) and "one compiled program per (class,
    B, engine)" stop being per-PR measurements: :func:`audit_entry`
    runs a real entry twice under the existing
    :class:`~cuvite_tpu.obs.compile_watch.CompileWatcher` — same slab
    class and B, different *content* — and reports B001 (a compiled
    module outside the closed manifest), B002 (the second run compiled
    ANYTHING: content reached a compile key), and B003 (compile count
    over the entry's budget).  ``tools/compile_audit.py`` is the CLI;
    ``tools/compile_budget.json`` is the checked-in manifest of
    (entry, slab class, B, engine) -> expected module set.

Everything jax-touching imports lazily: ``python -m cuvite_tpu.analysis``
(tiers 1/2) must keep running in environments with no jax at all.

Finding rule ids here (J*/B*) are deliberately OUTSIDE the R-rule
registry: they anchor on programs/entries, not source lines, and are
gated by tests/test_analysis.py + the audit CLI rather than the source
linter.  Severity follows the same vocabulary ("high" fails).
"""

from __future__ import annotations

import dataclasses
import json

import numpy as np

from cuvite_tpu.analysis.engine import Finding

# Dtypes that must not appear in a serving-path jaxpr (the 32-bit
# device contract; jax_enable_x64 oracle runs are out of audit scope).
WIDE_DTYPES = {"float64", "int64", "uint64", "complex128"}

# Primitive-name substrings that mark a host callback escape.
CALLBACK_PRIM_MARKERS = ("callback", "outside_call", "infeed", "outfeed")

# Primitives that move data between host and device inside the traced
# program (placement belongs to the driver, once per batch).
TRANSFER_PRIMS = {"device_put", "copy_to_host_async"}

MANIFEST_VERSION = 1


def _iter_eqns(jaxpr):
    """Every eqn of a (Closed)Jaxpr, recursing into sub-jaxprs (pjit
    bodies, while/cond/scan branches, shard_map bodies, ...)."""
    core_jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in core_jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                yield from _iter_eqns(sub)


def _sub_jaxprs(value):
    out = []
    stack = [value]
    while stack:
        v = stack.pop()
        if hasattr(v, "eqns") or hasattr(v, "jaxpr"):
            out.append(v)
        elif isinstance(v, (tuple, list)):
            stack.extend(v)
    return out


def lint_jaxpr(jaxpr, entry: str, allow: tuple = ()) -> list:
    """J001/J002/J003 findings for one traced program.  ``allow`` is a
    tuple of rule ids to skip (a manifest entry can grandfather a
    deliberate callback, say).  Findings anchor on the pseudo-path
    ``<jaxpr:ENTRY>`` with the primitive name as the snippet."""
    findings = []
    seen = set()

    def add(rule, prim, msg):
        if rule in allow:
            return
        key = (rule, prim)
        if key in seen:  # one finding per (rule, primitive) per entry
            return
        seen.add(key)
        findings.append(Finding(
            rule=rule, severity="high", path=f"<jaxpr:{entry}>", line=0,
            message=msg, snippet=prim))

    for eqn in _iter_eqns(jaxpr):
        prim = eqn.primitive.name
        if any(m in prim for m in CALLBACK_PRIM_MARKERS):
            add("J002", prim,
                f"host callback primitive '{prim}' inside the traced "
                f"program '{entry}': a hidden device->host round trip "
                "per execution (and a donation/buffer hazard under "
                "shard_map); keep host work outside the program")
        if prim in TRANSFER_PRIMS:
            add("J003", prim,
                f"'{prim}' inside the traced program '{entry}': "
                "host/device placement belongs to the driver (one "
                "upload per packed batch), not inside the compiled "
                "program")
        for var in list(eqn.outvars) + list(eqn.invars):
            aval = getattr(var, "aval", None)
            dt = getattr(aval, "dtype", None)
            if dt is not None and str(dt) in WIDE_DTYPES:
                add("J001", f"{prim}:{dt}",
                    f"64-bit dtype {dt} flows through '{prim}' in the "
                    f"traced program '{entry}': the device path is "
                    "32-bit by contract (graftlint R003's runtime "
                    "twin) — packed keys/ids corrupt silently without "
                    "x64, memory doubles with it")
                break
    return findings


# ---------------------------------------------------------------------------
# Representative serving-class workload (host-side, deterministic).


def tiny_graphs(b: int = 2, nv: int = 256, ne: int = 1024,
                content_seed: int = 1) -> list:
    """``b`` same-structure graphs at the representative small slab
    class (everything below the MIN_NV_PAD/MIN_NE_PAD floors pads to
    (4096, 16384)).  The edge STRUCTURE is fixed — so bucket plans and
    slab classes cannot drift between seeds — and only the weights vary
    with ``content_seed``: exactly the "batch content" PR 10's compile
    contract pins out of the compile key."""
    from cuvite_tpu.core.graph import Graph

    rng = np.random.default_rng(12345)  # structure: seed-INDEPENDENT
    graphs = []
    for j in range(b):
        src = np.concatenate([np.arange(nv), rng.integers(0, nv, ne - nv)])
        dst = np.concatenate([(np.arange(nv) + 1) % nv,
                              rng.integers(0, nv, ne - nv)])
        keep = src != dst
        wrng = np.random.default_rng(100_000 * (j + 1) + content_seed)
        w = wrng.uniform(0.5, 2.0, int(keep.sum()))
        graphs.append(Graph.from_edges(
            nv, src[keep].astype(np.int64), dst[keep].astype(np.int64),
            weights=w))
    return graphs


# ---------------------------------------------------------------------------
# Jaxpr tracing of the real batched-phase programs.


def trace_phase_jaxprs(b: int = 2, nv: int = 256, ne: int = 1024,
                       mesh=None, programs=None) -> dict:
    """{name: ClosedJaxpr} for the real batched per-phase programs at
    the representative class — the fused body, the bucketed phase-0
    body, and the coarse-class shrink.  Arg construction mirrors
    ``run_batched``'s upload block (host numpy stands in for the device
    placement; shapes and dtypes are identical).  ``mesh`` (a 1-D
    batch-axis Mesh) traces the SHARDED program the tier-5 mesh audit
    inspects — the shard_map body's collective sequence then appears in
    the jaxpr exactly as the compiled entry issues it.  ``programs``
    restricts to a subset of the three names (the mesh audit consumes
    one per entry; the bucket-plan build for an untraced program is
    pure waste)."""
    import jax

    from cuvite_tpu.core.batch import batch_bucket_plans, batch_slabs
    from cuvite_tpu.louvain.batched import (
        MAX_TOTAL_ITERATIONS,
        _batch_accum_name,
        _batched_coalesce_engine,
        _coarse_class,
        _get_batched_phase,
        _shrink_batch,
    )

    batch = batch_slabs(tiny_graphs(b=b, nv=nv, ne=ne))
    nv_pad = batch.nv_pad
    B = batch.b_pad
    wdt = np.dtype(np.float32)
    adt = _batch_accum_name(batch)
    eng = _batched_coalesce_engine(nv_pad, adt)
    comm_all = np.broadcast_to(
        np.arange(nv_pad, dtype=np.int32)[None, :], (B, nv_pad)).copy()
    prev = np.full((B,), -1.0, dtype=wdt)
    slab_args = (batch.src, batch.dst, batch.w, comm_all,
                 batch.real_mask, prev, batch.row_valid, batch.constant,
                 np.asarray(1.0e-6, dtype=wdt))

    want = set(programs) if programs is not None else {
        "batched_fused_phase", "batched_bucketed_phase0",
        "batched_coarse_shrink"}
    out = {}
    if "batched_fused_phase" in want:
        fused = _get_batched_phase(mesh, nv_pad, adt, eng,
                                   MAX_TOTAL_ITERATIONS)
        out["batched_fused_phase"] = jax.make_jaxpr(fused)(*slab_args)

    if "batched_bucketed_phase0" in want:
        bplan = batch_bucket_plans(batch)
        plan_args = (
            tuple((v.astype(np.int32), d, ww)
                  for v, d, ww in bplan.buckets),
            tuple(bplan.heavy),
            bplan.self_loop,
            bplan.perm,
        )
        bucketed = _get_batched_phase(mesh, nv_pad, adt, eng,
                                      MAX_TOTAL_ITERATIONS,
                                      engine="bucketed",
                                      n_buckets=len(bplan.buckets))
        out["batched_bucketed_phase0"] = jax.make_jaxpr(bucketed)(
            *plan_args, *slab_args)

    if "batched_coarse_shrink" in want:
        cnv, cne = _coarse_class(nv_pad, batch.ne_pad)
        out["batched_coarse_shrink"] = jax.make_jaxpr(
            lambda s, d, w, m: _shrink_batch(s, d, w, m, cnv=cnv,
                                             cne=cne))(
            batch.src, batch.dst, batch.w, batch.real_mask)
    return out


def audit_jaxprs(allow: dict | None = None, **kw) -> list:
    """Trace + lint every serving-path program; ``allow`` maps entry
    name -> tuple of J-rule ids to skip."""
    allow = allow or {}
    findings = []
    for name, jaxpr in trace_phase_jaxprs(**kw).items():
        findings.extend(lint_jaxpr(jaxpr, name,
                                   allow=tuple(allow.get(name, ()))))
    return findings


# ---------------------------------------------------------------------------
# Compile-budget audit.


@dataclasses.dataclass
class AuditResult:
    """One entry's audit: what compiled, what the manifest thought,
    and whether content leaked into a compile key."""

    entry: str
    observed: list          # modules compiled by the first run
    recompiled: list        # modules compiled by the content-changed run
    findings: list          # B001/B002/B003 Finding objects

    @property
    def ok(self) -> bool:
        return not self.findings


def observed_modules(watcher) -> list:
    """Module names a CompileWatcher saw (completed or in flight)."""
    return [e["module"] for e in watcher.events]


def _match(module: str, patterns) -> bool:
    return any(p in module for p in patterns)


def audit_entry(entry: str, run, manifest_entry: dict | None,
                seeds=(1, 2), extra_patterns=()) -> AuditResult:
    """Run ``run(content_seed)`` twice under the compile watcher and
    grade it against one manifest entry (see tools/compile_budget.json;
    None = entry missing from the manifest, which fails closed).

    The first run may compile (cold) or not (warm process): the audit
    requires observed ⊆ the manifest's module patterns and count <=
    ``max_compiles``.  ``extra_patterns`` widens the match set — the
    CLI passes the UNION of every manifest entry's modules, because
    per-entry attribution depends on jit-cache warmth and entry order
    (the serve path compiles nothing after the batched entries ran, but
    compiles THEIR modules when audited alone); the closed-set property
    lives at the manifest level, not per entry.  The second run changes
    ONLY content (same slab class, B, engine): with
    ``content_independent`` set (the default), ANY compile it triggers
    is a B002 — content reached a compile key.
    """
    from cuvite_tpu.obs.compile_watch import CompileWatcher

    with CompileWatcher() as w1:
        run(seeds[0])
    with CompileWatcher() as w2:
        run(seeds[1])
    observed = observed_modules(w1)
    recompiled = observed_modules(w2)
    findings = []
    if manifest_entry is None:
        findings.append(Finding(
            rule="B001", severity="high", path=f"<compile:{entry}>",
            line=0, snippet="",
            message=f"entry '{entry}' is not in the compile-budget "
                    "manifest (tools/compile_budget.json): the expected "
                    "compile set is CLOSED — add the entry deliberately "
                    "via tools/compile_audit.py --write-manifest"))
        return AuditResult(entry, observed, recompiled, findings)
    patterns = list(manifest_entry.get("modules", [])) \
        + list(extra_patterns)
    for mod in observed:
        if not _match(mod, patterns):
            findings.append(Finding(
                rule="B001", severity="high", path=f"<compile:{entry}>",
                line=0, snippet=mod,
                message=f"'{entry}' compiled module '{mod}' which "
                        "matches nothing in the manifest: a NEW compiled "
                        "program appeared on the serving path — extend "
                        "the manifest deliberately (--write-manifest) "
                        "or find what stopped reusing its program"))
    if manifest_entry.get("content_independent", True) and recompiled:
        findings.append(Finding(
            rule="B002", severity="high", path=f"<compile:{entry}>",
            line=0, snippet=", ".join(sorted(set(recompiled))[:4]),
            message=f"'{entry}' recompiled {len(recompiled)} module(s) "
                    "when only batch CONTENT changed (same class, B, "
                    "engine): content has entered a compile key — the "
                    "amortization contract (one program per class/B/"
                    "engine; weights pinned f32) is broken"))
    max_c = manifest_entry.get("max_compiles")
    if max_c is not None and len(observed) > max_c:
        findings.append(Finding(
            rule="B003", severity="high", path=f"<compile:{entry}>",
            line=0, snippet=str(len(observed)),
            message=f"'{entry}' compiled {len(observed)} modules, over "
                    f"the manifest budget of {max_c}: compile-cache "
                    "bloat (or a per-shape/per-value recompile) crept "
                    "in"))
    return AuditResult(entry, observed, recompiled, findings)


def load_manifest(path: str) -> dict:
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    if data.get("version") != MANIFEST_VERSION:
        raise ValueError(f"compile budget manifest {path!r}: unsupported "
                         f"version {data.get('version')!r}")
    return data


def write_manifest(path: str, entries: dict, env: dict) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": MANIFEST_VERSION, "env": env,
                   "entries": entries}, fh, indent=2, sort_keys=True)
        fh.write("\n")
