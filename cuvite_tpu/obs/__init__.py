"""cuvite_tpu.obs — the flight recorder (ISSUE 6).

Structured observability for every Louvain run, in four pieces:

  * ``events``        — span/event JSONL trace (sinks, SpanEmitter,
                        round-trip readers/validators);
  * ``compile_watch`` — the reusable XLA compile watcher (promoted out
                        of workloads/bench.py);
  * ``memory``        — the per-buffer HBM ledger + RSS + opt-in
                        jax.profiler hooks;
  * ``convergence``   — host decode of the device phase-loop telemetry
                        (per-iteration Q / moved / overflow rows);
  * ``recorder``      — FlightRecorder bundling the above behind one
                        context manager, attached to runs via
                        ``utils.trace.Tracer(recorder=...)``.

Everything except ``recorder.__enter__``'s watcher/profiler hooks is
stdlib-only: importable (and cheap) in bare CI containers.
"""

from cuvite_tpu.obs.compile_watch import CompileWatcher
from cuvite_tpu.obs.convergence import (
    MOVED_UNTRACKED,
    ConvRow,
    PhaseConvergence,
    convergence_summary,
    decode_phase_conv,
)
from cuvite_tpu.obs.events import (
    TRACE_VERSION,
    JsonlTraceSink,
    MemoryTraceSink,
    SpanEmitter,
    TraceSink,
    read_trace,
    spans_of,
    validate_trace,
)
from cuvite_tpu.obs.memory import DeviceMemoryLedger, save_memory_profile
from cuvite_tpu.obs.recorder import NO_TRACE, FlightRecorder

__all__ = [
    "CompileWatcher", "ConvRow", "DeviceMemoryLedger", "FlightRecorder",
    "JsonlTraceSink", "MemoryTraceSink", "MOVED_UNTRACKED", "NO_TRACE",
    "PhaseConvergence", "SpanEmitter", "TraceSink", "TRACE_VERSION",
    "convergence_summary", "decode_phase_conv",
    "read_trace", "save_memory_profile", "spans_of", "validate_trace",
]
