"""Reusable XLA compile watcher (promoted out of workloads/bench.py).

The bench harness grew a compile guard in round 6 so a number requiring
mid-measurement compilation could never enter a record; the same signal
— jax's ``Compiling <module> ...`` / ``Finished XLA compilation of
<module> in <secs> sec`` warnings under ``jax_log_compiles`` — is the
only visibility any run has into XLA compile cost, not just benches.
This module makes it a subscriber any caller can install: the driver
wires it to the trace sink (every compile becomes a ``compile`` event
with module name + duration), and the bench keeps using ``compiles`` as
its abort signal.

Nesting-safe: the prior ``jax_log_compiles`` value is restored on exit,
so a watcher inside a watched region (a traced run under the bench
guard) does not silently disarm the outer watcher.
"""

from __future__ import annotations

import logging
import re

_FINISHED_RE = re.compile(
    r"Finished XLA compilation of (.+?) in ([0-9.eE+-]+) sec")


def _module_of(compiling_msg: str) -> str:
    # "Compiling <name> with global shapes and types [...]" (pxla).
    body = compiling_msg.split("Compiling ", 1)[-1]
    return body.split(" with global shapes", 1)[0].strip()


class CompileWatcher(logging.Handler):
    """Collects XLA compile activity while active.

    ``compiles``: the raw ``Compiling ...`` messages (the bench guard's
    abort signal — identical semantics to the historical in-bench
    watcher and ``test_no_recompile_on_second_run``).
    ``events``: one dict per compile, ``{"module": name, "dur_s": secs}``
    (``dur_s`` is None when no matching completion message arrived,
    e.g. a compile still in flight at exit).  ``on_event`` (optional
    callable) receives each completed event as it happens — the trace
    subscriber hook.
    """

    def __init__(self, on_event=None):
        super().__init__(level=logging.WARNING)
        self.compiles: list = []
        self.events: list = []
        self.on_event = on_event
        self._pending: list = []  # modules compiling, completion not seen

    def emit(self, record):
        msg = record.getMessage()
        if "Compiling " in msg:
            self.compiles.append(msg)
            self._pending.append(_module_of(msg))
            return
        m = _FINISHED_RE.search(msg)
        if m:
            name, secs = m.group(1), float(m.group(2))
            # Pair the completion with its pending compile: exact name
            # first, else the LONGEST pending substring (completion says
            # "jit(<name>)").  Oldest-first substring matching let a
            # module whose name prefixes another ('step' vs 'step2')
            # steal the wrong completion and leave a phantom
            # dur_s=None event for the real one at exit.
            if name in self._pending:
                self._pending.remove(name)
            else:
                hits = [p for p in self._pending if p in name]
                if hits:
                    self._pending.remove(max(hits, key=len))
            self._record({"module": name, "dur_s": secs})

    def _record(self, ev: dict) -> None:
        self.events.append(ev)
        if self.on_event is not None:
            self.on_event(ev)

    def __enter__(self):
        import jax

        self._logger = logging.getLogger("jax")
        # Keep the compile chatter off stderr while watching: jax's own
        # StreamHandler lives directly on the 'jax' logger — mute it for
        # the window (restored on exit).  Other CompileWatchers are NOT
        # muted: a nested watcher must leave the outer one recording
        # (the nesting-safe contract above).
        self._muted = [(h, h.level) for h in self._logger.handlers
                       if h is not self and not isinstance(h, CompileWatcher)]
        for h, _ in self._muted:
            h.setLevel(logging.CRITICAL)
        self._logger.addHandler(self)
        self._prior_flag = bool(jax.config.jax_log_compiles)
        jax.config.update("jax_log_compiles", True)
        return self

    def __exit__(self, *exc):
        import jax

        jax.config.update("jax_log_compiles", self._prior_flag)
        self._logger.removeHandler(self)
        for h, lvl in self._muted:
            h.setLevel(lvl)
        # Compiles whose completion never arrived still become events.
        for pend in self._pending:
            self._record({"module": pend, "dur_s": None})
        self._pending = []
        return False
