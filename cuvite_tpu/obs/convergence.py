"""Per-phase convergence telemetry: the curves the literature tunes on.

The parallel-Louvain line (Ghosh et al., arXiv:1410.1237; Staudt &
Meyerhenke, arXiv:1304.4453) drives its heuristics — early termination,
coloring schedules, threshold cycling — off per-iteration convergence
curves: modularity gain and moved-vertex counts.  Our jitted phase loops
compute exactly those values every iteration and used to throw them
away, because fetching them per iteration would cost one blocking
device->host sync each (the thing the on-device loop exists to avoid).

The loops now accumulate one (Q, moved, overflow) row per iteration into
fixed-size device buffers (``core.types.CONV_ROWS_CAP`` rows) carried
through the ``lax.while_loop``; the buffers ride the EXISTING one-sync-
per-phase scalar fetch (driver.py::_phase_sync), so telemetry adds zero
host syncs.  This module is the host-side decode: raw buffers ->
:class:`PhaseConvergence` rows (surfaced as ``LouvainResult.convergence``
and emitted as ``convergence`` trace events).

Stdlib-only (no jax import): decoding operates on host arrays the sync
already fetched.
"""

from __future__ import annotations

import dataclasses

# Sentinel for "not tracked": host-loop schedules (coloring / class
# plans) know per-iteration Q from their existing per-iteration sync but
# never fetch the moved count (doing so would add syncs); their rows
# carry this instead of a real count.
MOVED_UNTRACKED = -1


@dataclasses.dataclass
class ConvRow:
    """One iteration of one phase."""

    iteration: int
    # Modularity of this iteration's INPUT assignment (what the step
    # computes — step.py's StepOut.modularity): row i's moves show up in
    # row i+1's q.  Row 0's q is the phase's starting assignment; the
    # phase's RESULTING modularity is the driver's scalar sync, not
    # rows[-1].q (the final sweep is the one that failed the threshold).
    q: float
    moved: int             # vertices THIS iteration moved (-1: untracked)
    overflow: bool = False  # sparse-exchange budget overflow this sweep

    def to_dict(self) -> dict:
        return {"iteration": self.iteration, "q": self.q,
                "moved": self.moved, "overflow": self.overflow}


@dataclasses.dataclass
class PhaseConvergence:
    """Per-iteration convergence rows of one phase attempt.

    ``gained`` — whether the phase beat the threshold and entered the
    result's phase list (the final attempt of a run typically does not).
    ``truncated`` — the phase ran more iterations than CONV_ROWS_CAP;
    rows beyond the cap were dropped on device (``rows`` holds the first
    CAP iterations; the scalar iteration count is still exact).
    """

    phase: int
    rows: list           # list[ConvRow]
    iterations: int      # exact device count (may exceed len(rows))
    truncated: bool = False
    gained: bool | None = None

    def dq(self) -> list:
        """Per-iteration modularity gains.  Because ``q`` is the INPUT-
        assignment modularity, ``dq()[i] = q[i] - q[i-1]`` is the gain
        realized by iteration i-1's moves (pair it with ``rows[i-1].
        moved``, not ``rows[i].moved``); None for row 0 — no earlier
        iteration of this phase produced its assignment."""
        out = []
        for i, r in enumerate(self.rows):
            out.append(None if i == 0 else r.q - self.rows[i - 1].q)
        return out

    def moved_total(self) -> int | None:
        """Total moved vertices, or None when any row is untracked."""
        if any(r.moved == MOVED_UNTRACKED for r in self.rows):
            return None
        return sum(r.moved for r in self.rows)

    def to_dict(self) -> dict:
        return {
            "phase": self.phase,
            "iterations": self.iterations,
            "truncated": self.truncated,
            "gained": self.gained,
            "rows": [r.to_dict() for r in self.rows],
        }

    def summary(self) -> dict:
        """Compact per-phase digest (bench schema v4's
        ``convergence_summary`` entries): endpoints instead of the full
        curve, so a record stays small at any iteration count.
        ``q_first``/``q_last`` keep the rows' input-assignment semantics
        (q_last is the final sweep's STARTING Q; the phase's resulting
        modularity lives in ``PhaseStats.modularity``)."""
        first = self.rows[0] if self.rows else None
        last = self.rows[-1] if self.rows else None
        mt = self.moved_total()
        return {
            "phase": self.phase,
            "iterations": self.iterations,
            "q_first": None if first is None else first.q,
            "q_last": None if last is None else last.q,
            "moved_first": None if first is None else first.moved,
            "moved_total": mt,
            "truncated": self.truncated,
            "gained": self.gained,
        }


def decode_phase_conv(phase: int, iterations: int, q_rows, moved_rows=None,
                      ovf_rows=None, gained=None) -> PhaseConvergence:
    """Host decode of the device conv buffers for one phase.

    ``q_rows``/``moved_rows``/``ovf_rows`` are the synced fixed-size
    buffers (length CONV_ROWS_CAP); only the first min(iterations, CAP)
    rows are meaningful.  ``moved_rows=None`` marks an untracked
    schedule (host color loops)."""
    cap = len(q_rows)
    n = min(int(iterations), cap)
    rows = []
    for i in range(n):
        rows.append(ConvRow(
            iteration=i,
            q=float(q_rows[i]),
            moved=(MOVED_UNTRACKED if moved_rows is None
                   else int(moved_rows[i])),
            overflow=bool(ovf_rows[i]) if ovf_rows is not None else False,
        ))
    return PhaseConvergence(
        phase=phase, rows=rows, iterations=int(iterations),
        truncated=int(iterations) > cap, gained=gained,
    )


def convergence_summary(convergence) -> list:
    """Bench schema v4 ``convergence_summary``: one digest per phase
    attempt (empty list when the run carried no telemetry)."""
    if not convergence:
        return []
    return [pc.summary() for pc in convergence]
