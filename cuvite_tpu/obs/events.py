"""Structured span/event trace: the flight recorder's record stream.

The reference instruments every stage with per-rank MPI_Wtime pairs and
routes diagnostics to per-rank ``dat.out.<rank>`` streams
(/root/reference/main.cpp:241-258, :101-110); that gives a human a wall
of text per run.  This module gives machines (and the regression gate)
the same information as a structured JSONL stream instead: nested SPANS
(begin/end pairs with ids, host/phase tags, wall-clock + monotonic
timestamps) and point EVENTS (exchange-plan stats, per-phase convergence
rows, XLA compiles, HBM snapshots).

One record per line, self-describing via the ``t`` field:

    {"t": "run_begin", "v": 1, "wall": ..., "mono": ..., "host": 0,
     "attrs": {...}}
    {"t": "span_begin", "id": 3, "parent": 2, "name": "iterate",
     "phase": 1, "host": 0, "wall": ..., "mono": ..., "attrs": {...}}
    {"t": "span_end", "id": 3, "wall": ..., "mono": ..., "dur_s": 0.12}
    {"t": "event", "name": "exchange", "parent": 2, "phase": 1,
     "host": 0, "wall": ..., "mono": ..., "attrs": {...}}

``wall`` is ``time.time()`` (cross-host alignable), ``mono`` is
``time.perf_counter()`` (duration-exact within one process).  Sinks are
anything with ``emit(dict)``/``close()``; the JSONL sink is the file
exporter behind ``--trace-out``, the memory sink backs tests.

Everything here is stdlib-only (no jax import): emission must stay cheap
enough to thread through the drivers unconditionally, and importable in
bare CI containers (the same contract as ``cuvite_tpu/analysis``).
"""

from __future__ import annotations

import dataclasses
import json
import time

TRACE_VERSION = 1


def jsonable(obj):
    """Best-effort conversion of attrs to JSON-serializable values:
    numpy arrays/scalars (matched by duck type, so numpy stays
    unimported here), dataclasses, sets, and nested containers."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, dict):
        return {str(k): jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set, frozenset)):
        return [jsonable(v) for v in obj]
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return jsonable(dataclasses.asdict(obj))
    if hasattr(obj, "tolist"):  # numpy array / scalar
        return jsonable(obj.tolist())
    if hasattr(obj, "item"):    # 0-d array-likes without tolist
        return jsonable(obj.item())
    return repr(obj)


class TraceSink:
    """Record consumer interface: ``emit(record)`` + ``close()``."""

    def emit(self, record: dict) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def close(self) -> None:
        pass


class MemoryTraceSink(TraceSink):
    """In-memory sink (tests; programmatic consumers)."""

    def __init__(self):
        self.records: list[dict] = []

    def emit(self, record: dict) -> None:
        self.records.append(record)


class JsonlTraceSink(TraceSink):
    """Line-buffered JSONL file sink (the ``--trace-out`` exporter).

    The file opens lazily on the first record and truncates any previous
    run's trace (same rerun semantics as ShardDiag's per-rank streams).
    """

    def __init__(self, path: str):
        self.path = path
        self._f = None

    def emit(self, record: dict) -> None:
        if self._f is None:
            import os

            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            # buffering=1 = real line buffering: a killed run (the
            # post-mortem case a flight recorder exists for) keeps every
            # fully-written record on disk.
            self._f = open(self.path, "w", encoding="utf-8", buffering=1)
        self._f.write(json.dumps(record, separators=(",", ":")) + "\n")

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


class SpanEmitter:
    """Nested-span bookkeeping over a sink: monotonically increasing span
    ids, a parent stack, and the (host, phase) tags every record carries.
    The drivers emit from the host control loop only (device work is
    traced via the compile/profiler hooks, not from inside jit).

    Thread-aware since ISSUE 14: the pipelined dispatcher's packer and
    executor stages emit concurrently, so the parent stack is
    PER-THREAD (a packer's ``pack`` span can never adopt the executor's
    events, and ending a span only unwinds the ending thread's own
    stack) and id allocation + sink emission serialize under one lock
    (interleaved records stay well-formed JSONL).  Single-threaded
    callers see the exact pre-ISSUE-14 behavior."""

    def __init__(self, sink: TraceSink, host: int = 0):
        import threading

        self.sink = sink
        self.host = int(host)
        self.phase = None
        self._next_id = 1
        self._stacks: dict = {}     # thread ident -> [span ids]
        self._open: set[int] = set()
        self._lock = threading.Lock()
        self._emit_base("run_begin", v=TRACE_VERSION)

    def _stack_here(self) -> list:
        import threading

        return self._stacks.setdefault(threading.get_ident(), [])

    def _emit_base(self, t: str, **fields) -> None:
        rec = {"t": t, "wall": time.time(), "mono": time.perf_counter(),
               "host": self.host}
        if self.phase is not None:
            rec["phase"] = int(self.phase)
        rec.update(fields)
        self.sink.emit(rec)

    def begin(self, name: str, **attrs) -> int:
        stack = self._stack_here()
        with self._lock:
            sid = self._next_id
            self._next_id += 1
            parent = stack[-1] if stack else None
            self._emit_base("span_begin", id=sid, parent=parent, name=name,
                            attrs=jsonable(attrs))
            self._open.add(sid)
        stack.append(sid)
        return sid

    def end(self, sid: int, dur_s: float | None = None, **attrs) -> None:
        stack = self._stack_here()
        with self._lock:
            if sid not in self._open:
                # Stale, double-ended, or another thread's handle:
                # dropping it beats unwinding this thread's open stack
                # as "leaked" over one bad caller.
                return
            # Close any nested spans left open by a non-local exit
            # first (THIS thread's only), so "every span closes" holds
            # even on an exception path.
            while stack and stack[-1] != sid:
                leaked = stack.pop()
                self._open.discard(leaked)
                self._emit_base("span_end", id=leaked, leaked=True)
            if stack and stack[-1] == sid:
                stack.pop()
            self._open.discard(sid)
            rec = {"id": sid}
            if dur_s is not None:
                rec["dur_s"] = float(dur_s)
            if attrs:
                rec["attrs"] = jsonable(attrs)
            self._emit_base("span_end", **rec)

    def event(self, name: str, **attrs) -> None:
        stack = self._stack_here()
        with self._lock:
            parent = stack[-1] if stack else None
            self._emit_base("event", name=name, parent=parent,
                            attrs=jsonable(attrs))

    def close(self) -> None:
        with self._lock:
            # Unwind every thread's leftover spans (the emitter's
            # "every span closes" guarantee, now per-thread).
            for stack in self._stacks.values():
                while stack:
                    sid = stack.pop()
                    if sid in self._open:
                        self._open.discard(sid)
                        self._emit_base("span_end", id=sid)
            self._emit_base("run_end")
            self.sink.close()


def read_trace(path: str) -> list[dict]:
    """Load a JSONL trace back into a record list (the round-trip side
    of :class:`JsonlTraceSink`)."""
    records = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line:
                records.append(json.loads(line))
    return records


def validate_trace(records: list) -> list:
    """Structural-violation strings for a record stream (empty = valid):
    every span_begin has exactly one span_end, span_end ids exist, parent
    spans are open at child begin time, and per-record ``mono`` never
    decreases (one process writes the stream in order)."""
    problems = []
    open_spans: set = set()
    ended: set = set()
    last_mono = None
    for i, rec in enumerate(records):
        t = rec.get("t")
        mono = rec.get("mono")
        if mono is None:
            problems.append(f"record {i}: missing mono timestamp")
        elif last_mono is not None and mono < last_mono:
            problems.append(f"record {i}: mono went backwards")
        else:
            last_mono = mono
        if t == "span_begin":
            sid = rec.get("id")
            if sid in open_spans or sid in ended:
                problems.append(f"record {i}: duplicate span id {sid}")
            parent = rec.get("parent")
            if parent is not None and parent not in open_spans:
                problems.append(
                    f"record {i}: span {sid} parent {parent} not open")
            open_spans.add(sid)
        elif t == "span_end":
            sid = rec.get("id")
            if sid not in open_spans:
                problems.append(
                    f"record {i}: span_end for unknown/closed id {sid}")
            else:
                open_spans.discard(sid)
                ended.add(sid)
    for sid in sorted(open_spans):
        problems.append(f"span {sid} never closed")
    return problems


def spans_of(records: list, name: str | None = None) -> list:
    """The closed spans of a record stream as dicts with ``begin``/
    ``end`` records, children span ids and child events attached."""
    begins = {r["id"]: r for r in records if r.get("t") == "span_begin"}
    ends = {r["id"]: r for r in records if r.get("t") == "span_end"}
    out = []
    for sid, b in begins.items():
        if name is not None and b.get("name") != name:
            continue
        children = [r["id"] for r in begins.values()
                    if r.get("parent") == sid]
        events = [r for r in records
                  if r.get("t") == "event" and r.get("parent") == sid]
        out.append({"id": sid, "begin": b, "end": ends.get(sid),
                    "name": b.get("name"), "children": children,
                    "events": events,
                    "child_names": sorted(
                        begins[c].get("name") for c in children)})
    out.sort(key=lambda s: s["id"])
    return out
