"""FlightRecorder: one handle bundling the observability surfaces.

The drivers already accept a ``tracer``; a recorder rides on it
(``Tracer(recorder=...)``) and gives the run:

  * a :class:`~cuvite_tpu.obs.events.SpanEmitter` over a sink (JSONL
    file for ``--trace-out``, memory for tests/bench),
  * a :class:`~cuvite_tpu.obs.memory.DeviceMemoryLedger` fed by the
    PhaseRunner/fused uploads and snapshotted at phase boundaries,
  * an installed :class:`~cuvite_tpu.obs.compile_watch.CompileWatcher`
    (context-managed) turning every XLA compile into a ``compile``
    event — the bench guard's signal, available to ANY run,
  * the opt-in ``jax.profiler`` hooks under ``profile_dir``.

Use as a context manager around the run::

    with FlightRecorder(JsonlTraceSink(path)) as rec:
        louvain_phases(g, tracer=Tracer(recorder=rec))

``__exit__`` uninstalls the watcher, stops the profiler session, emits
the run_end record and closes the sink (every span closes — the
emitter unwinds leaked spans itself).
"""

from __future__ import annotations

from cuvite_tpu.obs.compile_watch import CompileWatcher
from cuvite_tpu.obs.events import (
    JsonlTraceSink,
    MemoryTraceSink,
    SpanEmitter,
    TraceSink,
)
from cuvite_tpu.obs.memory import DeviceMemoryLedger, save_memory_profile

# Sentinel sink: the recorder is attached for its compile watcher /
# HBM ledger only and keeps NO emitter at all (bench, --metrics-out
# without --trace-out).  Tracer's facade no-ops on emitter=None, so
# span/event payloads — including the per-phase convergence row dicts —
# are never built, and no unread record list grows for the process
# lifetime.
NO_TRACE = object()


class FlightRecorder:
    def __init__(self, sink: TraceSink | None = None, host: int = 0,
                 profile_dir: str | None = None,
                 watch_compiles: bool = True):
        if sink is NO_TRACE:
            self.sink = None
            self.emitter = None
        else:
            self.sink = sink if sink is not None else MemoryTraceSink()
            self.emitter = SpanEmitter(self.sink, host=host)
        self.ledger = DeviceMemoryLedger()
        self.profile_dir = profile_dir
        self.compile_events: list = []
        # Raw jax "Compiling ..." messages (the bench guard's abort
        # signal; aliased to the watcher's list so it survives __exit__).
        self.compile_log: list = []
        self._watch_compiles = watch_compiles
        self._watcher = None
        self._profiling = False

    # -- lifecycle ----------------------------------------------------------
    def __enter__(self) -> "FlightRecorder":
        if self._watch_compiles:
            self._watcher = CompileWatcher(on_event=self._on_compile)
            self.compile_log = self._watcher.compiles
            self._watcher.__enter__()
        if self.profile_dir:
            import os

            import jax

            os.makedirs(self.profile_dir, exist_ok=True)
            jax.profiler.start_trace(self.profile_dir)
            self._profiling = True
            if self.emitter is not None:
                self.emitter.event("profiler_start", dir=self.profile_dir)
        return self

    def __exit__(self, *exc) -> bool:
        if self._watcher is not None:
            self._watcher.__exit__(*exc)
            self._watcher = None
        if self._profiling:
            import jax

            jax.profiler.stop_trace()
            self._profiling = False
            path = save_memory_profile(self.profile_dir, "final")
            if self.emitter is not None:
                self.emitter.event("profiler_stop", dir=self.profile_dir,
                                   memory_profile=path)
        self.close()
        return False

    def close(self) -> None:
        if self.emitter is None:
            return
        if self.ledger.peak_by_buffer:
            self.emitter.event("hbm_peak",
                               peak_by_buffer=self.ledger.peak_by_buffer)
        self.emitter.close()

    # -- subscribers --------------------------------------------------------
    def _on_compile(self, ev: dict) -> None:
        self.compile_events.append(ev)
        if self.emitter is not None:
            self.emitter.event("compile", **ev)

    # -- programmatic access ------------------------------------------------
    @property
    def records(self) -> list:
        """The record list when the sink is a MemoryTraceSink (tests and
        the bench); raises otherwise."""
        return self.sink.records
