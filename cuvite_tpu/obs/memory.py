"""Device-memory (HBM) ledger + host RSS accounting + profiler hooks.

The reference tracks only the host high-water (getrusage ru_maxrss,
/root/reference/main.cpp:142-150).  On TPU the number that actually
gates scale is per-chip HBM — the round-8 finding was that the
replicated exchange's O(nv_total) per-chip tables, not transport, bind
the sparse cutover — and XLA gives no per-buffer attribution for the
arrays a driver uploads.  The ledger closes that gap at the level the
driver controls: every logical buffer the PhaseRunner/fused driver
places (slab, tables, plans, exchange routing) is recorded by category
with its ``nbytes``, snapshotted at phase boundaries, and the per-
category peak survives the run (bench schema v4's
``hbm_peak_by_buffer``).

Byte counts are LOGICAL global sizes (``arr.nbytes`` of the placed
array): what the driver asked for, before any XLA padding/donation —
i.e. the number a capacity model needs, not an allocator dump.  The
opt-in ``jax.profiler`` hooks below are the allocator-truth complement.
"""

from __future__ import annotations

import os


def per_device_nbytes(a) -> int:
    """The bytes ONE device holds for an array: the max over devices of
    that device's addressable shard bytes.  A replicated placement
    answers the full ``nbytes`` (every device holds a copy), an evenly
    1-D-sharded one ``nbytes / n_devices`` — which is exactly the
    number the tier-5 replication audit (analysis/meshcheck.py, M003)
    grades against the declared scaling law.  Host arrays and anything
    without sharding metadata count as replicated (conservative)."""
    nb = int(getattr(a, "nbytes", 0) or 0)
    try:
        shards = a.addressable_shards
    except Exception:
        return nb
    per: dict = {}
    try:
        for s in shards:
            did = getattr(s.device, "id", s.device)
            per[did] = per.get(did, 0) + int(s.data.nbytes)
    except Exception:
        return nb
    return max(per.values()) if per else nb


class DeviceMemoryLedger:
    """Per-category device-buffer byte accounting.

    ``begin_phase()`` clears the live set (a new PhaseRunner replaces
    the previous phase's buffers); ``track(category, *arrays)`` adds the
    nbytes of anything array-like (None and scalars are ignored);
    ``snapshot(phase)`` returns the live totals and folds them into the
    running per-category peaks (``peak_by_buffer``).

    Two parallel books are kept per category: LOGICAL global bytes
    (``arr.nbytes`` — what the driver asked for) and PER-DEVICE bytes
    (:func:`per_device_nbytes` — what one chip actually holds, read off
    the placement's sharding).  The per-device column is the tier-5
    export: ``tools/mesh_audit.py`` grades it against the declared
    scaling laws in ``tools/replication_budget.json`` (a "sharded"
    category whose per-device bytes stop shrinking with the mesh is the
    O(nv_total)-per-chip replication creep round-8 measured).
    """

    CATEGORIES = ("slab", "tables", "plans", "exchange",
                  "exchange_grouped", "scratch")

    def __init__(self):
        self.live: dict = {}
        self.live_per_device: dict = {}
        self.peak_by_buffer: dict = {}
        self.peak_per_device: dict = {}
        self.snapshots: list = []

    def begin_phase(self) -> None:
        self.live = {}
        self.live_per_device = {}

    def track(self, category: str, *arrays) -> None:
        n = 0
        nd = 0
        for a in arrays:
            if a is None:
                continue
            nb = getattr(a, "nbytes", None)
            if nb:
                n += int(nb)
                nd += per_device_nbytes(a)
        if n:
            self.live[category] = self.live.get(category, 0) + n
            self.live_per_device[category] = \
                self.live_per_device.get(category, 0) + nd

    def track_nbytes(self, category: str, nbytes: int) -> None:
        if nbytes:
            self.live[category] = self.live.get(category, 0) + int(nbytes)
            self.live_per_device[category] = \
                self.live_per_device.get(category, 0) + int(nbytes)

    def snapshot(self, phase=None) -> dict:
        from cuvite_tpu.utils.trace import rss_high_water_mb

        by_buffer = dict(self.live)
        per_device = dict(self.live_per_device)
        for k, v in by_buffer.items():
            if v > self.peak_by_buffer.get(k, 0):
                self.peak_by_buffer[k] = v
        for k, v in per_device.items():
            if v > self.peak_per_device.get(k, 0):
                self.peak_per_device[k] = v
        snap = {
            "phase": phase,
            "by_buffer": by_buffer,
            "per_device": per_device,
            "total": sum(by_buffer.values()),
            "rss_mb": round(rss_high_water_mb(), 1),
        }
        self.snapshots.append(snap)
        return snap


def save_memory_profile(profile_dir: str | None, tag: str) -> str | None:
    """Opt-in ``jax.profiler.save_device_memory_profile`` snapshot (pprof
    format) under ``profile_dir``; returns the path, or None when
    disabled or the profiler is unavailable on this backend."""
    if not profile_dir:
        return None
    import jax

    os.makedirs(profile_dir, exist_ok=True)
    path = os.path.join(profile_dir, f"memory.{tag}.prof")
    try:
        jax.profiler.save_device_memory_profile(path)
    except Exception:  # backend without memory profiling: opt-in, so soft
        return None
    return path
