"""Device-memory (HBM) ledger + host RSS accounting + profiler hooks.

The reference tracks only the host high-water (getrusage ru_maxrss,
/root/reference/main.cpp:142-150).  On TPU the number that actually
gates scale is per-chip HBM — the round-8 finding was that the
replicated exchange's O(nv_total) per-chip tables, not transport, bind
the sparse cutover — and XLA gives no per-buffer attribution for the
arrays a driver uploads.  The ledger closes that gap at the level the
driver controls: every logical buffer the PhaseRunner/fused driver
places (slab, tables, plans, exchange routing) is recorded by category
with its ``nbytes``, snapshotted at phase boundaries, and the per-
category peak survives the run (bench schema v4's
``hbm_peak_by_buffer``).

Byte counts are LOGICAL global sizes (``arr.nbytes`` of the placed
array): what the driver asked for, before any XLA padding/donation —
i.e. the number a capacity model needs, not an allocator dump.  The
opt-in ``jax.profiler`` hooks below are the allocator-truth complement.
"""

from __future__ import annotations

import os


class DeviceMemoryLedger:
    """Per-category device-buffer byte accounting.

    ``begin_phase()`` clears the live set (a new PhaseRunner replaces
    the previous phase's buffers); ``track(category, *arrays)`` adds the
    nbytes of anything array-like (None and scalars are ignored);
    ``snapshot(phase)`` returns the live totals and folds them into the
    running per-category peaks (``peak_by_buffer``).
    """

    CATEGORIES = ("slab", "tables", "plans", "exchange", "scratch")

    def __init__(self):
        self.live: dict = {}
        self.peak_by_buffer: dict = {}
        self.snapshots: list = []

    def begin_phase(self) -> None:
        self.live = {}

    def track(self, category: str, *arrays) -> None:
        n = 0
        for a in arrays:
            if a is None:
                continue
            nb = getattr(a, "nbytes", None)
            if nb:
                n += int(nb)
        if n:
            self.live[category] = self.live.get(category, 0) + n

    def track_nbytes(self, category: str, nbytes: int) -> None:
        if nbytes:
            self.live[category] = self.live.get(category, 0) + int(nbytes)

    def snapshot(self, phase=None) -> dict:
        from cuvite_tpu.utils.trace import rss_high_water_mb

        by_buffer = dict(self.live)
        for k, v in by_buffer.items():
            if v > self.peak_by_buffer.get(k, 0):
                self.peak_by_buffer[k] = v
        snap = {
            "phase": phase,
            "by_buffer": by_buffer,
            "total": sum(by_buffer.values()),
            "rss_mb": round(rss_high_water_mb(), 1),
        }
        self.snapshots.append(snap)
        return snap


def save_memory_profile(profile_dir: str | None, tag: str) -> str | None:
    """Opt-in ``jax.profiler.save_device_memory_profile`` snapshot (pprof
    format) under ``profile_dir``; returns the path, or None when
    disabled or the profiler is unavailable on this backend."""
    if not profile_dir:
        return None
    import jax

    os.makedirs(profile_dir, exist_ok=True)
    path = os.path.join(profile_dir, f"memory.{tag}.prof")
    try:
        jax.profiler.save_device_memory_profile(path)
    except Exception:  # backend without memory profiling: opt-in, so soft
        return None
    return path
