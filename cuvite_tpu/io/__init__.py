"""cuvite_tpu.io"""
