"""Vite binary graph format reader/writer.

On-disk layout (cf. loadDistGraphMPIIO, /root/reference/distgraph.cpp:99-197):

    [nv: GraphElem] [ne: GraphElem]
    [edgeListIndexes: (nv+1) x GraphElem]
    [edges: ne x Edge{tail: GraphElem, weight: GraphWeight}]

GraphElem/GraphWeight are int64/double by default, or int32/float when the
reference is compiled with `USE_32_BIT_GRAPH` (/root/reference/edge.hpp:10-20).
The Edge struct has no padding in either width.

Reads use `np.memmap`, so a multi-host deployment can read only its vertex
range (the analog of the per-rank `MPI_File_read_at` slices,
/root/reference/distgraph.cpp:130-190).
"""

from __future__ import annotations

import numpy as np

from cuvite_tpu.core.graph import Graph
from cuvite_tpu.core.types import Policy, default_policy, wide_policy


def _elem_dtype(bits64: bool) -> np.dtype:
    return np.dtype("<i8") if bits64 else np.dtype("<i4")


def _edge_dtype(bits64: bool) -> np.dtype:
    if bits64:
        return np.dtype([("tail", "<i8"), ("weight", "<f8")])
    return np.dtype([("tail", "<i4"), ("weight", "<f4")])


def read_vite(
    path: str,
    bits64: bool = True,
    policy: Policy | None = None,
    vertex_range: tuple[int, int] | None = None,
) -> Graph:
    """Read a Vite binary graph (optionally only ``[lo, hi)`` vertex rows).

    When ``vertex_range`` is given, the returned CSR is the local slice with
    offsets re-based to start at 0 (cf. /root/reference/distgraph.cpp:194-197).
    """
    policy = policy or (wide_policy() if bits64 else default_policy())
    elem = _elem_dtype(bits64)
    edge = _edge_dtype(bits64)
    header = np.fromfile(path, dtype=elem, count=2)
    if len(header) != 2:
        raise ValueError(f"{path}: truncated Vite header")
    nv, ne = int(header[0]), int(header[1])
    import os

    expected = 2 * elem.itemsize + (nv + 1) * elem.itemsize + ne * edge.itemsize
    actual = os.path.getsize(path)
    if nv < 0 or ne < 0 or actual < expected:
        raise ValueError(
            f"{path}: header (nv={nv}, ne={ne}) implies {expected} bytes but "
            f"file has {actual} — wrong bits64={bits64} flag or corrupt file"
        )
    lo, hi = (0, nv) if vertex_range is None else vertex_range
    if not (0 <= lo <= hi <= nv):
        raise ValueError(f"bad vertex range {lo, hi} for nv={nv}")

    offsets_map = np.memmap(
        path, dtype=elem, mode="r", offset=2 * elem.itemsize, shape=(nv + 1,)
    )
    offsets = np.array(offsets_map[lo : hi + 1], dtype=np.int64)
    e0, e1 = int(offsets[0]), int(offsets[-1])
    if e0 < 0 or e1 > ne or np.any(np.diff(offsets) < 0):
        raise ValueError(
            f"{path}: non-monotone CSR offsets — wrong bits64={bits64} flag "
            f"or corrupt file"
        )
    from cuvite_tpu import native

    if (e1 - e0) >= native.MIN_NATIVE_EDGES and native.available():
        # Native bulk read: one sequential fread + parallel deinterleave
        # (the numpy memmap path does two strided passes over the edge
        # records).  Offsets were already read and validated above.
        tails_n, weights_n = native.vite_edges(path, bits64, nv, e0, e1)
        return Graph(
            offsets=offsets - e0,
            tails=tails_n.astype(policy.vertex_dtype),
            weights=weights_n.astype(policy.weight_dtype),
            policy=policy,
        )
    edges_offset = 2 * elem.itemsize + (nv + 1) * elem.itemsize
    edges_map = np.memmap(
        path, dtype=edge, mode="r", offset=edges_offset + e0 * edge.itemsize,
        shape=(e1 - e0,),
    )
    tails = np.array(edges_map["tail"], dtype=policy.vertex_dtype)
    weights = np.array(edges_map["weight"], dtype=policy.weight_dtype)
    return Graph(
        offsets=offsets - e0,
        tails=tails,
        weights=weights,
        policy=policy,
    )


def write_vite(path: str, graph: Graph, bits64: bool = True) -> None:
    """Write a graph in the Vite binary format
    (cf. writeGraph, /root/reference/distgraph.cpp:936-1014)."""
    elem = _elem_dtype(bits64)
    edge = _edge_dtype(bits64)
    nv = graph.num_vertices
    ne = graph.num_edges
    from cuvite_tpu import native

    if ne >= native.MIN_NATIVE_EDGES and native.available():
        native.vite_write(
            path, bits64, graph.offsets,
            graph.tails.astype(np.int64),
            graph.weights.astype(np.float64),
        )
        return
    with open(path, "wb") as f:
        np.array([nv, ne], dtype=elem).tofile(f)
        graph.offsets.astype(elem).tofile(f)
        rec = np.empty(ne, dtype=edge)
        rec["tail"] = graph.tails
        rec["weight"] = graph.weights
        rec.tofile(f)
