"""Vite binary graph format reader/writer.

On-disk layout (cf. loadDistGraphMPIIO, /root/reference/distgraph.cpp:99-197):

    [nv: GraphElem] [ne: GraphElem]
    [edgeListIndexes: (nv+1) x GraphElem]
    [edges: ne x Edge{tail: GraphElem, weight: GraphWeight}]

GraphElem/GraphWeight are int64/double by default, or int32/float when the
reference is compiled with `USE_32_BIT_GRAPH` (/root/reference/edge.hpp:10-20).
The Edge struct has no padding in either width.

Reads use `np.memmap`, so a multi-host deployment can read only its vertex
range (the analog of the per-rank `MPI_File_read_at` slices,
/root/reference/distgraph.cpp:130-190).
"""

from __future__ import annotations

import numpy as np

from cuvite_tpu.core.graph import Graph
from cuvite_tpu.core.types import Policy, default_policy, wide_policy


def _elem_dtype(bits64: bool) -> np.dtype:
    return np.dtype("<i8") if bits64 else np.dtype("<i4")


def _edge_dtype(bits64: bool) -> np.dtype:
    if bits64:
        return np.dtype([("tail", "<i8"), ("weight", "<f8")])
    return np.dtype([("tail", "<i4"), ("weight", "<f4")])


def read_vite(
    path: str,
    bits64: bool = True,
    policy: Policy | None = None,
    vertex_range: tuple[int, int] | None = None,
) -> Graph:
    """Read a Vite binary graph (optionally only ``[lo, hi)`` vertex rows).

    When ``vertex_range`` is given, the returned CSR is the local slice with
    offsets re-based to start at 0 (cf. /root/reference/distgraph.cpp:194-197).
    """
    policy = policy or (wide_policy() if bits64 else default_policy())
    elem = _elem_dtype(bits64)
    edge = _edge_dtype(bits64)
    header = np.fromfile(path, dtype=elem, count=2)
    if len(header) != 2:
        raise ValueError(f"{path}: truncated Vite header")
    nv, ne = int(header[0]), int(header[1])
    import os

    expected = 2 * elem.itemsize + (nv + 1) * elem.itemsize + ne * edge.itemsize
    actual = os.path.getsize(path)
    if nv < 0 or ne < 0 or actual < expected:
        raise ValueError(
            f"{path}: header (nv={nv}, ne={ne}) implies {expected} bytes but "
            f"file has {actual} — wrong bits64={bits64} flag or corrupt file"
        )
    lo, hi = (0, nv) if vertex_range is None else vertex_range
    if not (0 <= lo <= hi <= nv):
        raise ValueError(f"bad vertex range {lo, hi} for nv={nv}")

    offsets_map = np.memmap(
        path, dtype=elem, mode="r", offset=2 * elem.itemsize, shape=(nv + 1,)
    )
    offsets = np.array(offsets_map[lo : hi + 1], dtype=np.int64)
    e0, e1 = int(offsets[0]), int(offsets[-1])
    if e0 < 0 or e1 > ne or np.any(np.diff(offsets) < 0):
        raise ValueError(
            f"{path}: non-monotone CSR offsets — wrong bits64={bits64} flag "
            f"or corrupt file"
        )
    from cuvite_tpu import native

    if (e1 - e0) >= native.MIN_NATIVE_EDGES and native.available():
        # Native bulk read: one sequential fread + parallel deinterleave
        # (the numpy memmap path does two strided passes over the edge
        # records).  Offsets were already read and validated above.
        tails_n, weights_n = native.vite_edges(path, bits64, nv, e0, e1)
        return Graph(
            offsets=offsets - e0,
            tails=tails_n.astype(policy.vertex_dtype),
            weights=weights_n.astype(policy.weight_dtype),
            policy=policy,
        )
    edges_offset = 2 * elem.itemsize + (nv + 1) * elem.itemsize
    edges_map = np.memmap(
        path, dtype=edge, mode="r", offset=edges_offset + e0 * edge.itemsize,
        shape=(e1 - e0,),
    )
    tails = np.array(edges_map["tail"], dtype=policy.vertex_dtype)
    weights = np.array(edges_map["weight"], dtype=policy.weight_dtype)
    return Graph(
        offsets=offsets - e0,
        tails=tails,
        weights=weights,
        policy=policy,
    )


class ViteStreamWriter:
    """Chunked Vite-format writer for graphs too large to hold as a
    ``Graph`` (the workloads converters / synthesizer path).

    The caller supplies the final ``(nv, ne)`` and the CSR offsets up
    front (a two-pass pipeline computes degrees first), then fills edge
    records in arbitrary slices via :meth:`write_edges`; RSS stays
    O(chunk), never O(ne).  The produced file is byte-compatible with
    :func:`write_vite` for the same CSR content.
    """

    def __init__(self, path: str, nv: int, ne: int, bits64: bool = True):
        if nv < 0 or ne < 0:
            raise ValueError(f"bad shape nv={nv}, ne={ne}")
        self.path = path
        self.nv = nv
        self.ne = ne
        self.bits64 = bits64
        self._elem = _elem_dtype(bits64)
        self._edge = _edge_dtype(bits64)
        if not bits64 and (nv > np.iinfo(np.int32).max
                           or ne > np.iinfo(np.int32).max):
            raise ValueError(
                f"nv={nv} / ne={ne} overflow the 32-bit Vite layout; "
                "pass bits64=True")
        self._edges_offset = 2 * self._elem.itemsize \
            + (nv + 1) * self._elem.itemsize
        total = self._edges_offset + ne * self._edge.itemsize
        with open(path, "wb") as f:
            np.array([nv, ne], dtype=self._elem).tofile(f)
            f.truncate(total)
        self._offsets_written = False
        # One persistent r+ memmap over the edge-record region: slice
        # assignment writes through without reopening per chunk.
        self._edges_mm = (np.memmap(path, dtype=self._edge, mode="r+",
                                    offset=self._edges_offset, shape=(ne,))
                          if ne else None)

    def write_offsets(self, offsets: np.ndarray) -> None:
        offsets = np.asarray(offsets, dtype=np.int64)
        if (len(offsets) != self.nv + 1 or offsets[0] != 0
                or offsets[-1] != self.ne
                or np.any(np.diff(offsets) < 0)):
            raise ValueError("offsets must be monotone, [0 .. ne], len nv+1")
        mm = np.memmap(self.path, dtype=self._elem, mode="r+",
                       offset=2 * self._elem.itemsize, shape=(self.nv + 1,))
        mm[:] = offsets.astype(self._elem)
        mm.flush()
        del mm
        self._offsets_written = True

    def write_edges(self, index: np.ndarray | int, tails: np.ndarray,
                    weights: np.ndarray) -> None:
        """Write edge records at ``index`` (an int start for a contiguous
        slice, or a per-edge position array for scatter placement)."""
        rec = np.empty(len(tails), dtype=self._edge)
        rec["tail"] = tails
        rec["weight"] = weights
        if isinstance(index, (int, np.integer)):
            self._edges_mm[int(index):int(index) + len(rec)] = rec
        else:
            self._edges_mm[np.asarray(index, dtype=np.int64)] = rec

    def read_edges(self, lo: int, hi: int) -> np.ndarray:
        """Read back a record slice (the canonicalization pass needs it)."""
        return np.array(self._edges_mm[lo:hi])

    def close(self) -> None:
        if not self._offsets_written:
            raise ValueError(f"{self.path}: offsets were never written")
        if self._edges_mm is not None:
            self._edges_mm.flush()
            del self._edges_mm
            self._edges_mm = None


def write_vite(path: str, graph: Graph, bits64: bool = True) -> None:
    """Write a graph in the Vite binary format
    (cf. writeGraph, /root/reference/distgraph.cpp:936-1014)."""
    elem = _elem_dtype(bits64)
    edge = _edge_dtype(bits64)
    nv = graph.num_vertices
    ne = graph.num_edges
    from cuvite_tpu import native

    if ne >= native.MIN_NATIVE_EDGES and native.available():
        native.vite_write(
            path, bits64, graph.offsets,
            graph.tails.astype(np.int64),
            graph.weights.astype(np.float64),
        )
        return
    with open(path, "wb") as f:
        np.array([nv, ne], dtype=elem).tofile(f)
        graph.offsets.astype(elem).tofile(f)
        rec = np.empty(ne, dtype=edge)
        rec["tail"] = graph.tails
        rec["weight"] = graph.weights
        rec.tofile(f)
