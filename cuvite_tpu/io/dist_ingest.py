"""Per-host sharded ingest: each process reads ONLY its shards' edge ranges.

The TPU-native analog of the reference's collective MPI-IO load
(loadDistGraphMPIIO[Balanced], /root/reference/distgraph.cpp:69-337): every
rank seeks to its own offset slice and reads its vertex range's edges.  Here
each PROCESS of a multi-host run issues `read_vite(vertex_range=...)` range
reads for the shards its devices own, so no host ever materializes the full
O(ne) edge list — host memory is O(local edges + nv), matching the per-chip
O(owned + ghosts) device story.

What stays replicated (all O(nv) or smaller, computed identically on every
process): the partition table, the padded-id maps, the full weighted-degree
vector (assembled once by an allgather of per-process blocks — the analog of
the reference's degree Allreduce, louvain.cpp:2153-2183), and phase >= 1
coarse graphs (assembled by allgathering per-process aggregated coarse
edges, the analog of send_newEdges routing, rebuild.cpp:281-428).
"""

from __future__ import annotations

import dataclasses
import functools

import numpy as np

from cuvite_tpu.comm.multihost import (
    allgather_varlen, allreduce_sum_host, local_shard_range,
)
from cuvite_tpu.core.distgraph import (
    Shard, balanced_parts_from_offsets, uniform_parts,
)
from cuvite_tpu.core.graph import Graph
from cuvite_tpu.core.types import Policy, default_policy, next_pow2, wide_policy
from cuvite_tpu.io.vite import _elem_dtype, read_vite


@dataclasses.dataclass
class GraphMeta:
    """Stand-in for `Graph` where only metadata is needed (per-host ingest
    never holds the full edge list)."""

    num_vertices: int
    num_edges: int
    policy: Policy
    tw2: float

    def total_edge_weight_twice(self) -> float:
        return self.tw2


@dataclasses.dataclass
class DistVite:
    """DistGraph-compatible partition whose edge slabs exist only for the
    shards owned by THIS process (remote shards carry ``src=None``).

    Duck-types the `DistGraph` surface the sparse bucketed SPMD path uses;
    `graph` is a `GraphMeta`, so full-graph host consumers (the host
    modularity oracle, host coarsening) must use the `modularity()` /
    `coarse_edges()` methods instead, which reduce over local slabs and
    combine across processes.
    """

    graph: GraphMeta
    parts: np.ndarray
    nshards: int
    nv_pad: int
    ne_pad: int
    shards: list
    local_lo: int        # first shard index owned by this process
    local_hi: int        # one past last owned shard index
    vdeg_full: np.ndarray  # [nshards*nv_pad] padded weighted degrees

    local_only = True    # marks the per-host-ingest layout for PhaseRunner

    @property
    def num_vertices(self) -> int:
        return self.graph.num_vertices

    @property
    def num_edges(self) -> int:
        return self.graph.num_edges

    @property
    def total_vertices(self) -> int:
        return self.graph.num_vertices

    @property
    def total_padded_vertices(self) -> int:
        return self.nshards * self.nv_pad

    @property
    def total_edges(self) -> int:
        return self.graph.num_edges

    @functools.cached_property
    def old_to_pad(self) -> np.ndarray:
        nv = self.graph.num_vertices
        out = np.empty(nv, dtype=np.int64)
        for s in range(self.nshards):
            lo, hi = int(self.parts[s]), int(self.parts[s + 1])
            out[lo:hi] = s * self.nv_pad + np.arange(hi - lo)
        return out

    @functools.cached_property
    def pad_to_old(self) -> np.ndarray:
        out = np.full(self.total_padded_vertices, -1, dtype=np.int64)
        for s in range(self.nshards):
            lo, hi = int(self.parts[s]), int(self.parts[s + 1])
            out[s * self.nv_pad: s * self.nv_pad + (hi - lo)] = np.arange(
                lo, hi)
        return out

    def padded_weighted_degrees(self) -> np.ndarray:
        return self.vdeg_full

    def vertex_mask(self) -> np.ndarray:
        return self.pad_to_old >= 0

    def _to_pad(self, v: np.ndarray) -> np.ndarray:
        """Original global ids -> padded global ids without the O(nv) map."""
        owner = np.searchsorted(self.parts, v, side="right") - 1
        return owner * self.nv_pad + (v - self.parts[owner])

    @staticmethod
    def load(path: str, nshards: int, bits64: bool = True,
             balanced: bool = False, policy: Policy | None = None,
             min_nv_pad: int = 1, min_ne_pad: int = 1) -> "DistVite":
        policy = policy or (wide_policy() if bits64 else default_policy())
        elem = _elem_dtype(bits64)
        header = np.fromfile(path, dtype=elem, count=2)
        if len(header) != 2:
            raise ValueError(f"{path}: truncated Vite header")
        nv, ne = int(header[0]), int(header[1])
        offsets = np.memmap(path, dtype=elem, mode="r",
                            offset=2 * elem.itemsize, shape=(nv + 1,))
        if balanced:
            parts = balanced_parts_from_offsets(offsets, nv, ne, nshards)
        else:
            parts = uniform_parts(nv, nshards)
        owned = np.diff(parts)
        nv_pad = next_pow2(max(int(owned.max()) if len(owned) else 1,
                               min_nv_pad, 1))
        counts = np.asarray(offsets)[parts[1:]] - np.asarray(offsets)[parts[:-1]]
        ne_pad = next_pow2(max(int(counts.max()) if len(counts) else 1,
                               min_ne_pad, 1))

        lo, hi = local_shard_range(nshards)
        vdt = policy.vertex_dtype
        wdt = policy.weight_dtype
        shards = []
        local_wsum = 0.0
        vdeg_blocks = np.zeros((hi - lo) * nv_pad, dtype=np.float64)
        dv = DistVite(
            graph=GraphMeta(nv, ne, policy, 0.0), parts=parts,
            nshards=nshards, nv_pad=nv_pad, ne_pad=ne_pad, shards=shards,
            local_lo=lo, local_hi=hi, vdeg_full=None,
        )
        for s in range(nshards):
            p0, p1 = int(parts[s]), int(parts[s + 1])
            n = int(counts[s])
            if not (lo <= s < hi):
                shards.append(Shard(base=p0, bound=p1, src=None, dst=None,
                                    w=None, n_real_edges=n))
                continue
            gs = read_vite(path, bits64=bits64, policy=policy,
                           vertex_range=(p0, p1))
            src_l = np.full(ne_pad, nv_pad, dtype=vdt)
            dst_g = np.zeros(ne_pad, dtype=vdt)
            w = np.zeros(ne_pad, dtype=wdt)
            src_l[:n] = gs.sources()
            tails = gs.tails.astype(np.int64)
            dst_g[:n] = dv._to_pad(tails).astype(vdt)
            w[:n] = gs.weights
            shards.append(Shard(base=p0, bound=p1, src=src_l, dst=dst_g,
                                w=w, n_real_edges=n))
            blk = (s - lo) * nv_pad
            deg = np.bincount(gs.sources(),
                              weights=gs.weights.astype(np.float64),
                              minlength=p1 - p0)
            vdeg_blocks[blk: blk + (p1 - p0)] = deg
            local_wsum += float(gs.weights.sum(dtype=np.float64))

        # Degree Allreduce analog: per-process padded blocks -> full vector
        # (process blocks are contiguous in shard order).
        gathered = allgather_varlen(vdeg_blocks)
        dv.vdeg_full = np.concatenate(gathered).astype(wdt)
        assert len(dv.vdeg_full) == nshards * nv_pad
        dv.graph.tw2 = float(allreduce_sum_host(local_wsum))
        return dv

    # ---- full-graph stand-ins (distributed reductions) --------------------

    def content_fingerprint(self) -> int:
        """Checkpoint fingerprint from per-shard content hashes combined
        across processes (the DistVite analog of
        utils.checkpoint.graph_fingerprint; VERDICT r4 item 7).

        Hashes each owned shard's (base, bound, n, src, dst, w) and crc-chains
        the allgathered per-shard digests in shard order, so every process
        computes the same value without any host ever holding the full
        edge list.  The digest covers the PARTITIONED layout: a resume
        must use the same ingest mode and nshards (a stricter guard than
        the full-ingest fingerprint, failing closed on partition drift)."""
        import zlib

        digests = []
        for s in range(self.local_lo, self.local_hi):
            sh = self.shards[s]
            n = int(sh.n_real_edges)
            h = zlib.crc32(
                np.asarray([sh.base, sh.bound, n], dtype=np.int64).tobytes())
            # src is REQUIRED content: without it, shifting the same dst
            # multiset across source rows (the row-boundary change
            # graph_fingerprint catches via CSR offsets) would collide.
            h = zlib.crc32(
                np.ascontiguousarray(sh.src[:n]).view(np.uint8), h)
            h = zlib.crc32(
                np.ascontiguousarray(sh.dst[:n]).view(np.uint8), h)
            h = zlib.crc32(np.ascontiguousarray(sh.w[:n]).view(np.uint8), h)
            digests.append(h)
        all_digests = np.concatenate(allgather_varlen(
            np.asarray(digests, dtype=np.int64)))
        h = 0
        for v in all_digests:
            h = zlib.crc32(np.int64(v).tobytes(), h)
        return (h << 16) ^ (self.num_vertices & 0xFFFF)

    def modularity(self, comm_pad: np.ndarray) -> float:
        """f64 modularity of padded-space labels: local-slab e-term +
        degree-vector a-term, combined across processes (the analog of
        distComputeModularity's Allreduce, louvain.cpp:2433-2481)."""
        comm_pad = np.asarray(comm_pad).astype(np.int64)
        e_local = 0.0
        for s in range(self.local_lo, self.local_hi):
            sh = self.shards[s]
            real = sh.src < self.nv_pad
            sg = s * self.nv_pad + sh.src[real].astype(np.int64)
            dg_ = sh.dst[real].astype(np.int64)
            same = comm_pad[sg] == comm_pad[dg_]
            e_local += float(
                sh.w[real][same].astype(np.float64).sum())
        e_xx = float(allreduce_sum_host(e_local))
        # a2: every process holds vdeg_full; sum degree per community once.
        a = np.bincount(comm_pad, weights=self.vdeg_full.astype(np.float64))
        c = 1.0 / self.graph.tw2
        return e_xx * c - float((a * a).sum()) * c * c

    def coarse_edges(self, dense_comm_pad: np.ndarray, nc: int):
        """Community->community edge triples for the next phase: aggregate
        local slabs, then allgather the (much smaller) per-process coarse
        triples (fill_newEdgesMap + send_newEdges analog,
        rebuild.cpp:244-428).  Returns (src, dst, w) for the FULL coarse
        graph on every process."""
        dense = np.asarray(dense_comm_pad).astype(np.int64)
        srcs, dsts, ws = [], [], []
        for s in range(self.local_lo, self.local_hi):
            sh = self.shards[s]
            real = sh.src < self.nv_pad
            sg = s * self.nv_pad + sh.src[real].astype(np.int64)
            srcs.append(dense[sg])
            dsts.append(dense[sh.dst[real].astype(np.int64)])
            ws.append(sh.w[real].astype(np.float64))
        if srcs:
            src = np.concatenate(srcs)
            dst = np.concatenate(dsts)
            w = np.concatenate(ws)
            # Local pre-aggregation bounds the allgather payload.
            glocal = Graph.from_edges(nc, src, dst, weights=w,
                                      symmetrize=False)
            src, dst, w = (glocal.sources().astype(np.int64),
                           glocal.tails.astype(np.int64),
                           glocal.weights.astype(np.float64))
        else:
            src = dst = np.zeros(0, dtype=np.int64)
            w = np.zeros(0, dtype=np.float64)
        all_src = np.concatenate(allgather_varlen(src))
        all_dst = np.concatenate(allgather_varlen(dst))
        all_w = np.concatenate(allgather_varlen(w))
        return all_src, all_dst, all_w
