"""In-memory graph generators.

- `generate_rgg`: random geometric graph with the reference's structure
  (/root/reference/distgraph.cpp:341-933): nv points in the unit square,
  shard s owning n=nv/p points whose Y coordinates live in the strip
  [s/p, (s+1)/p); an edge connects every pair within Euclidean distance
  rn = (rc + rt)/2 (distgraph.cpp:347-349), weighted by the distance.
  Coordinates come from the SAME Park-Miller LCG stream as the reference
  (X from slice [0, n), Y rescaled into the strip from slice [n, 2n) —
  distgraph.cpp:426-434), so the point set is bit-identical for a given
  (nv, nshards, seed=1).  Neighbor search uses a KD-tree instead of the
  reference's O(n^2) loops + up/down ghost Sendrecv (distgraph.cpp:483-620):
  same edge set, not a translation.  `-e` extra edges draw from a
  documented LCG stream slice (seed+1) with the reference's deterministic
  far-target weight function replicated bit-for-bit (see _rgg_extra_edges;
  the reference's own pair draws are time(0)^getpid()-seeded and therefore
  unreproducible even against itself, distgraph.cpp:706).
- `generate_rmat`: Graph500-style R-MAT generator (a=0.57, b=0.19, c=0.19)
  for the benchmark configs in BASELINE.md (not present in the reference,
  which defers non-RGG formats to external converters, README:36-40).
  Uses a counter-based SplitMix64 RNG so the numpy fallback and the native
  C++ fast path (native/cuvite_native.cpp) generate bit-identical graphs.
"""

from __future__ import annotations

import numpy as np
from scipy.spatial import cKDTree

from cuvite_tpu import native
from cuvite_tpu.core.graph import Graph
from cuvite_tpu.core.types import Policy, default_policy
from cuvite_tpu.utils.rng import lcg_stream, scramble_ids, splitmix64, u01


def rgg_radius(nv: int) -> float:
    """rn = (rc + rt)/2 (distgraph.cpp:347-349)."""
    rc = np.sqrt(np.log(nv) / (np.pi * nv))
    rt = np.sqrt(2.0736 / nv)
    return float((rc + rt) / 2.0)


def rgg_points(nv: int, nshards: int, seed: int = 1) -> tuple[np.ndarray, np.ndarray]:
    """Reference-parity coordinates: X uniform [0,1), Y in the owner's strip."""
    n = nv // nshards
    xs, ys = [], []
    for s in range(nshards):
        # Each shard draws 2n numbers from ITS OWN slice of the global
        # stream: LCG(seed) with rank offset s*2n (utils.hpp parallel
        # prefix with n_=2n per rank).
        r = lcg_stream(seed, nshards * 2 * n, lo=s * 2 * n, hi=(s + 1) * 2 * n)
        xs.append(r[:n])
        ys.append(s / nshards + r[n:] * (1.0 / nshards))  # rescale(lo, 1/p)
    return np.concatenate(xs), np.concatenate(ys)


def generate_rgg(
    nv: int,
    nshards: int = 1,
    random_edge_percent: int = 0,
    seed: int = 1,
    policy: Policy | None = None,
) -> Graph:
    """Random geometric graph equivalent to `-n nv` (+ optional `-p pct`)."""
    policy = policy or default_policy()
    n = nv // nshards
    nv_eff = n * nshards  # reference drops the remainder (distgraph.cpp:380)
    rn = rgg_radius(nv_eff)
    if nshards > 1 and 1.0 / nshards <= rn:
        raise ValueError(
            f"strip width 1/{nshards} must exceed rn={rn:.4f} "
            f"(distgraph.cpp:351)"
        )
    x, y = rgg_points(nv_eff, nshards, seed)
    pts = np.stack([x, y], axis=1)
    tree = cKDTree(pts)
    pairs = tree.query_pairs(r=rn, output_type="ndarray")  # i < j, ed <= rn
    d = np.sqrt(((pts[pairs[:, 0]] - pts[pairs[:, 1]]) ** 2).sum(axis=1))
    src, dst, w = pairs[:, 0], pairs[:, 1], d

    if random_edge_percent > 0:
        es, ed_, wx = _rgg_extra_edges(
            pts, nshards, n, nv_eff, random_edge_percent,
            len(pairs), np.stack([src, dst], axis=1), seed,
        )
        src = np.concatenate([src, es])
        dst = np.concatenate([dst, ed_])
        w = np.concatenate([w, wx])

    return Graph.from_edges(nv_eff, src, dst, weights=w, policy=policy)


def _rgg_extra_edges(pts, nshards, n, nv, pct, n_undirected, existing,
                     seed):
    """Extra long-range edges, ~pct% of the global undirected edge count
    (the `-e` flag; /root/reference/distgraph.cpp:652-842).

    Reference-parity semantics, with the deterministic pieces replicated
    exactly and the one non-reproducible piece replaced (and documented):

    - count: nrande = pct * total_undirected / 100, split evenly per rank
      with the remainder on the LAST rank; when nrande < nranks the whole
      count goes to the last rank (the reference leaves pnrande
      uninitialized for the other ranks there, distgraph.cpp:661-667 — a
      documented quirk; here they draw 0).
    - draws: rank r draws (local i in [0, n), global j in [0, nv)) pairs.
      The reference seeds this stream with time(0)^getpid()
      (distgraph.cpp:706) — NON-reproducible by design, so no bitwise
      cross-validation of the pair set is possible even between two runs
      of the reference itself.  Here the draws come from slice
      [2*r*quota, 2*(r+1)*quota) of the Park-Miller LCG stream for
      seed+1 (the same engine family as the reference's
      default_random_engine = minstd_rand0), making `-e` runs fully
      reproducible for a given (nv, nshards, seed).
    - skips forfeit the draw (reference `continue`): a self-pair or a
      duplicate of an existing/earlier edge reduces the inserted count,
      not re-drawn.  (The reference compares LOCAL indices for the
      self-test, distgraph.cpp:722, and checks only the (i, g_j)
      direction for duplicates, :728-731; here: global-id self-test and
      undirected duplicate test.)
    - weight: Euclidean distance when the target rank is self or a strip
      neighbor (the ranks whose coordinates the reference holds); for far
      targets the reference's deterministic hash-seeded weight
      uniform[0.01, 1.0) from minstd_rand0(g_i*nv + g_j) — replicated
      bit-for-bit in utils.rng.minstd0_uniform_real.
    """
    from cuvite_tpu.utils.rng import minstd0_uniform_real

    nrande = (pct * n_undirected) // 100
    if nrande <= 0:
        z = np.zeros(0, dtype=np.int64)
        return z, z, np.zeros(0, dtype=np.float64)
    counts = np.zeros(nshards, dtype=np.int64)
    if nrande < nshards:
        counts[-1] = nrande
    else:
        counts[:] = nrande // nshards
        counts[-1] += nrande % nshards
    offs = np.concatenate([[0], np.cumsum(counts)])
    total = int(offs[-1])
    gi_parts, gj_parts = [], []
    for r in range(nshards):
        c = int(counts[r])
        if c == 0:
            continue
        vals = lcg_stream(seed + 1, 2 * total,
                          lo=2 * int(offs[r]), hi=2 * int(offs[r + 1]))
        i_loc = np.minimum((vals[0::2] * n).astype(np.int64), n - 1)
        g_j = np.minimum((vals[1::2] * nv).astype(np.int64), nv - 1)
        gi_parts.append(r * n + i_loc)
        gj_parts.append(g_j)
    g_i = np.concatenate(gi_parts)
    g_j = np.concatenate(gj_parts)

    keep = g_i != g_j
    # Undirected duplicate check against the RGG edge set and earlier
    # extras (first occurrence wins, like the sequential insertion).
    lo_ = np.minimum(g_i, g_j)
    hi_ = np.maximum(g_i, g_j)
    key = lo_ * nv + hi_
    ex_key = (np.minimum(existing[:, 0], existing[:, 1]) * nv
              + np.maximum(existing[:, 0], existing[:, 1]))
    keep &= ~np.isin(key, ex_key)
    _, first = np.unique(key, return_index=True)
    is_first = np.zeros(len(key), dtype=bool)
    is_first[first] = True
    keep &= is_first
    g_i, g_j = g_i[keep], g_j[keep]

    owner_i = g_i // n
    owner_j = g_j // n
    near = np.abs(owner_i - owner_j) <= 1
    dist = np.sqrt(((pts[g_i] - pts[g_j]) ** 2).sum(axis=1))
    wfar = minstd0_uniform_real(
        (g_i.astype(np.uint64) * np.uint64(nv) + g_j.astype(np.uint64)),
        0.01, 1.0)
    return g_i, g_j, np.where(near, dist, wfar)


def rmat_edges_numpy(scale: int, ne: int, seed: int, a: float, b: float,
                     c: float) -> tuple[np.ndarray, np.ndarray]:
    """Pure-numpy R-MAT edge list; bit-identical to cv_rmat
    (native/cuvite_native.cpp).  Per edge e and recursion level l, the
    quadrant draws are splitmix64(seed + e*2*scale + 2l [+1])."""
    ab = a + b
    a_norm = a / ab
    c_norm = c / (1.0 - ab)
    base = (np.arange(ne, dtype=np.uint64) * np.uint64(2 * scale)
            + np.uint64(seed))
    src = np.zeros(ne, dtype=np.uint64)
    dst = np.zeros(ne, dtype=np.uint64)
    one = np.uint64(1)
    for level in range(scale):
        r1 = u01(splitmix64(base + np.uint64(2 * level)))
        r2 = u01(splitmix64(base + np.uint64(2 * level + 1)))
        sbit = r1 > ab
        dbit = np.where(sbit, r2 > c_norm, r2 > a_norm)
        src = (src << one) | sbit.astype(np.uint64)
        dst = (dst << one) | dbit.astype(np.uint64)
    src = scramble_ids(src, scale, seed).astype(np.int64)
    dst = scramble_ids(dst, scale, seed).astype(np.int64)
    return src, dst


def generate_rmat(
    scale: int,
    edge_factor: int = 16,
    seed: int = 1,
    a: float = 0.57,
    b: float = 0.19,
    c: float = 0.19,
    policy: Policy | None = None,
) -> Graph:
    """Graph500 R-MAT: 2^scale vertices, edge_factor * 2^scale edges
    (before dedup/symmetrization), unit weights."""
    policy = policy or default_policy()
    nv = 1 << scale
    ne = edge_factor << scale
    if native.available():
        src, dst = native.rmat_edges(scale, ne, seed, a, b, c)
    else:
        src, dst = rmat_edges_numpy(scale, ne, seed, a, b, c)
    keep = src != dst
    if scale < 31:
        # Hand int32 ids to the unit-weight CSR path and free the int64
        # generator output before ingest — at billion-edge scales the
        # 8-byte copies are the difference between fitting one host or
        # not (tools/scale_model.md).
        s32 = src[keep].astype(np.int32)
        del src
        d32 = dst[keep].astype(np.int32)
        del dst, keep
        return Graph.from_edges(nv, s32, d32, policy=policy)
    return Graph.from_edges(nv, src[keep], dst[keep], policy=policy)
