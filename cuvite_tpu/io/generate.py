"""In-memory graph generators.

- `generate_rgg`: random geometric graph with the reference's structure
  (/root/reference/distgraph.cpp:341-933): nv points in the unit square,
  shard s owning n=nv/p points whose Y coordinates live in the strip
  [s/p, (s+1)/p); an edge connects every pair within Euclidean distance
  rn = (rc + rt)/2 (distgraph.cpp:347-349), weighted by the distance.
  Coordinates come from the SAME Park-Miller LCG stream as the reference
  (X from slice [0, n), Y rescaled into the strip from slice [n, 2n) —
  distgraph.cpp:426-434), so the point set is bit-identical for a given
  (nv, nshards, seed=1).  Neighbor search uses a KD-tree instead of the
  reference's O(n^2) loops + up/down ghost Sendrecv (distgraph.cpp:483-620):
  same edge set, not a translation.
- `generate_rmat`: Graph500-style R-MAT generator (a=0.57, b=0.19, c=0.19)
  for the benchmark configs in BASELINE.md (not present in the reference,
  which defers non-RGG formats to external converters, README:36-40).
  Uses a counter-based SplitMix64 RNG so the numpy fallback and the native
  C++ fast path (native/cuvite_native.cpp) generate bit-identical graphs.
"""

from __future__ import annotations

import numpy as np
from scipy.spatial import cKDTree

from cuvite_tpu import native
from cuvite_tpu.core.graph import Graph
from cuvite_tpu.core.types import Policy, default_policy
from cuvite_tpu.utils.rng import lcg_stream, scramble_ids, splitmix64, u01


def rgg_radius(nv: int) -> float:
    """rn = (rc + rt)/2 (distgraph.cpp:347-349)."""
    rc = np.sqrt(np.log(nv) / (np.pi * nv))
    rt = np.sqrt(2.0736 / nv)
    return float((rc + rt) / 2.0)


def rgg_points(nv: int, nshards: int, seed: int = 1) -> tuple[np.ndarray, np.ndarray]:
    """Reference-parity coordinates: X uniform [0,1), Y in the owner's strip."""
    n = nv // nshards
    xs, ys = [], []
    for s in range(nshards):
        # Each shard draws 2n numbers from ITS OWN slice of the global
        # stream: LCG(seed) with rank offset s*2n (utils.hpp parallel
        # prefix with n_=2n per rank).
        r = lcg_stream(seed, nshards * 2 * n, lo=s * 2 * n, hi=(s + 1) * 2 * n)
        xs.append(r[:n])
        ys.append(s / nshards + r[n:] * (1.0 / nshards))  # rescale(lo, 1/p)
    return np.concatenate(xs), np.concatenate(ys)


def generate_rgg(
    nv: int,
    nshards: int = 1,
    random_edge_percent: int = 0,
    seed: int = 1,
    policy: Policy | None = None,
) -> Graph:
    """Random geometric graph equivalent to `-n nv` (+ optional `-p pct`)."""
    policy = policy or default_policy()
    n = nv // nshards
    nv_eff = n * nshards  # reference drops the remainder (distgraph.cpp:380)
    rn = rgg_radius(nv_eff)
    if nshards > 1 and 1.0 / nshards <= rn:
        raise ValueError(
            f"strip width 1/{nshards} must exceed rn={rn:.4f} "
            f"(distgraph.cpp:351)"
        )
    x, y = rgg_points(nv_eff, nshards, seed)
    pts = np.stack([x, y], axis=1)
    tree = cKDTree(pts)
    pairs = tree.query_pairs(r=rn, output_type="ndarray")  # i < j, ed <= rn
    d = np.sqrt(((pts[pairs[:, 0]] - pts[pairs[:, 1]]) ** 2).sum(axis=1))
    src, dst, w = pairs[:, 0], pairs[:, 1], d

    if random_edge_percent > 0:
        # Extra long-range edges, ~pct% of the local edge count
        # (distgraph.cpp:652-842).  Random pairs, weight = distance.
        n_extra = int(random_edge_percent * len(pairs)) // 100
        rng = np.random.default_rng(seed)
        es = rng.integers(0, nv_eff, size=n_extra)
        ed_ = rng.integers(0, nv_eff, size=n_extra)
        keep = es != ed_
        es, ed_ = es[keep], ed_[keep]
        wx = np.sqrt(((pts[es] - pts[ed_]) ** 2).sum(axis=1))
        src = np.concatenate([src, es])
        dst = np.concatenate([dst, ed_])
        w = np.concatenate([w, wx])

    return Graph.from_edges(nv_eff, src, dst, weights=w, policy=policy)


def rmat_edges_numpy(scale: int, ne: int, seed: int, a: float, b: float,
                     c: float) -> tuple[np.ndarray, np.ndarray]:
    """Pure-numpy R-MAT edge list; bit-identical to cv_rmat
    (native/cuvite_native.cpp).  Per edge e and recursion level l, the
    quadrant draws are splitmix64(seed + e*2*scale + 2l [+1])."""
    ab = a + b
    a_norm = a / ab
    c_norm = c / (1.0 - ab)
    base = (np.arange(ne, dtype=np.uint64) * np.uint64(2 * scale)
            + np.uint64(seed))
    src = np.zeros(ne, dtype=np.uint64)
    dst = np.zeros(ne, dtype=np.uint64)
    one = np.uint64(1)
    for level in range(scale):
        r1 = u01(splitmix64(base + np.uint64(2 * level)))
        r2 = u01(splitmix64(base + np.uint64(2 * level + 1)))
        sbit = r1 > ab
        dbit = np.where(sbit, r2 > c_norm, r2 > a_norm)
        src = (src << one) | sbit.astype(np.uint64)
        dst = (dst << one) | dbit.astype(np.uint64)
    src = scramble_ids(src, scale, seed).astype(np.int64)
    dst = scramble_ids(dst, scale, seed).astype(np.int64)
    return src, dst


def generate_rmat(
    scale: int,
    edge_factor: int = 16,
    seed: int = 1,
    a: float = 0.57,
    b: float = 0.19,
    c: float = 0.19,
    policy: Policy | None = None,
) -> Graph:
    """Graph500 R-MAT: 2^scale vertices, edge_factor * 2^scale edges
    (before dedup/symmetrization), unit weights."""
    policy = policy or default_policy()
    nv = 1 << scale
    ne = edge_factor << scale
    if native.available():
        src, dst = native.rmat_edges(scale, ne, seed, a, b, c)
    else:
        src, dst = rmat_edges_numpy(scale, ne, seed, a, b, c)
    keep = src != dst
    return Graph.from_edges(nv, src[keep], dst[keep], policy=policy)
