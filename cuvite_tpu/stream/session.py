"""Resident streaming session: delta application + warm-start
incremental re-clustering on the device slab (ISSUE 17).

A :class:`StreamSession` owns one tenant's device-resident canonical
edge slab (the single-shard layout of DistGraph.build / the fused
driver) across its lifetime:

  * ``apply_delta`` mutates the slab in HBM through THE chokepoint
    (stream/delta.py::apply_delta_slab), tracks the 2m fixup on the
    host in f64, folds the batch digest into the session's content
    **fingerprint lineage**, and accumulates the delta **frontier**
    (touched endpoints + slab neighbors) for the next warm start.
  * ``recluster`` re-runs the clustering with a ``--warm-start`` arm:
    ``labels`` seeds phase 0 from the previous run's composed labels
    and the ET active set from the accumulated frontier (reusing the
    driver's on-device ET phase loop via ``warm_start_phase``);
    ``plp`` seeds from a label-propagation prepass (the A/B
    alternative); ``cold`` is the from-scratch arm.  Later phases run
    the fused multi-phase program on the device-coarsened slab, so the
    whole re-cluster stays device-resident like the fused driver.

Stale warm-starts are refused LOUDLY: warm labels carry the fingerprint
of the slab content they were computed against, and ``recluster`` only
accepts them when that fingerprint equals the session's pre-delta
lineage point (the content the accumulated frontier measures edits
from).  A mismatch — labels from another session, another edit history,
or a skipped delta — raises instead of silently seeding wrong
communities, mirroring the checkpoint-resume fingerprint refusal
(utils/checkpoint.py, louvain_phases --resume).
"""

from __future__ import annotations

import time
import zlib

import jax
import jax.numpy as jnp
import numpy as np

from cuvite_tpu.coarsen.device import (
    device_compose_labels,
    device_coarsen_slab,
    device_renumber,
    device_weighted_degrees,
    grow_slab,
    maybe_shrink_to_class,
)
from cuvite_tpu.core.distgraph import DistGraph
from cuvite_tpu.core.types import TERMINATION_PHASE_COUNT, next_pow2
from cuvite_tpu.stream.delta import (
    DeltaBatch,
    apply_delta_slab,
    delta_frontier,
    plp_prepass,
)
from cuvite_tpu.utils.checkpoint import graph_fingerprint

WARM_MODES = ("labels", "plp", "cold")


def _fold_fingerprint(fp: int, digest: int) -> int:
    """Advance a content-fingerprint lineage by one canonical delta
    batch: deterministic in (fp, digest), so two sessions that applied
    the same edits to the same base agree, and any divergence — a
    missed batch, a different base — never collides back."""
    return zlib.crc32(np.int64(digest).tobytes(), fp & 0xFFFFFFFF) \
        ^ ((fp >> 16) << 8)


class StreamSession:
    """One tenant's resident slab + warm-start state (module docstring).

    Public state: ``src``/``dst``/``w`` (the canonical device slab),
    ``ne`` (real rows), ``nv``/``nv_pad``/``ne_pad``, ``tw2`` (2m, host
    f64), ``fingerprint`` (content lineage), ``frontier_frac`` (of the
    pending accumulated frontier).  Labels from the last ``recluster``
    are kept on host (O(V)) for warm seeding and serving replies.
    """

    def __init__(self, *, nv, nv_pad, ne_pad, ne, src, dst, w, tw2,
                 policy, fingerprint, tracer=None):
        if tracer is None:
            from cuvite_tpu.utils.trace import NullTracer

            tracer = NullTracer()
        self.nv = int(nv)
        self.nv_pad = int(nv_pad)
        self.ne_pad = int(ne_pad)
        self.ne = int(ne)
        self.src = src
        self.dst = dst
        self.w = w
        self.tw2 = float(tw2)
        self.policy = policy
        self.fingerprint = int(fingerprint)
        self.tracer = tracer
        self._labels: np.ndarray | None = None
        self._labels_fp: int | None = None
        # The lineage point the pending frontier accumulates from: warm
        # labels are valid iff their fingerprint equals this.
        self.frontier_base_fp = int(fingerprint)
        self._frontier = None           # device bool [nv_pad] or None
        self.frontier_frac = 0.0
        self.deltas_applied = 0

    # -- construction -------------------------------------------------------

    @staticmethod
    def from_graph(graph, *, tracer=None) -> "StreamSession":
        """Upload a host graph as a resident session (the returning
        tenant's ONE full-slab upload; every later visit pays the
        delta).  Same slab class floors as the fused driver, so the
        session re-enters the driver's compiled-step cache keys."""
        dg = DistGraph.build(graph, 1, min_nv_pad=4096, min_ne_pad=16384)
        sh = dg.shards[0]
        sess = StreamSession(
            nv=graph.num_vertices, nv_pad=dg.nv_pad, ne_pad=dg.ne_pad,
            ne=sh.n_real_edges,
            src=jnp.asarray(np.asarray(sh.src).astype(np.int32)),
            dst=jnp.asarray(np.asarray(sh.dst).astype(np.int32)),
            w=jnp.asarray(np.asarray(sh.w).astype(np.float32)),
            tw2=graph.total_edge_weight_twice(),
            policy=graph.policy,
            fingerprint=graph_fingerprint(graph),
            tracer=tracer)
        return sess

    # -- facts --------------------------------------------------------------

    @property
    def real_mask(self):
        return jnp.arange(self.nv_pad, dtype=jnp.int32) < jnp.int32(self.nv)

    def hbm_bytes(self) -> int:
        """Resident HBM footprint of the session (the StreamPool
        ledger's unit): the three slab arrays plus the O(nv_pad)
        frontier/mask state.  Host-side labels are not HBM."""
        return 12 * self.ne_pad + 2 * self.nv_pad

    def labels(self) -> np.ndarray | None:
        return None if self._labels is None else self._labels.copy()

    # -- delta ingestion ----------------------------------------------------

    def apply_delta(self, batch: DeltaBatch) -> dict:
        """Apply one canonical batch through the jitted chokepoint;
        returns ``{n_ins, n_del, n_del_hit, ne, frontier_frac,
        wall_s}``.  Inserts overflowing the padding headroom first lift
        the slab to the next pow2 class (grow_slab) — the only legal
        class transition, keeping the compile-key set bounded."""
        if batch.num_vertices != self.nv:
            raise ValueError(
                f"delta batch is for {batch.num_vertices} vertices; the "
                f"resident session has {self.nv}")
        t0 = time.perf_counter()
        if self.ne + batch.n_ins > self.ne_pad:
            new_ne_pad = next_pow2(self.ne + batch.n_ins)
            self.src, self.dst, self.w = grow_slab(
                self.src, self.dst, self.w, nv_pad=self.nv_pad,
                new_nv_pad=self.nv_pad, new_ne_pad=new_ne_pad)
            self.tracer.event("delta_spill", ne_pad=self.ne_pad,
                              new_ne_pad=new_ne_pad)
            self.ne_pad = new_ne_pad
        ins_s, ins_d, ins_w, del_s, del_d, _ = batch.padded()
        ins_mass = float(np.sum(batch.ins_w, dtype=np.float64))
        adt = self._accum()
        src2, dst2, w2, ne2_d, del_w_d, nhit_d = apply_delta_slab(
            self.src, self.dst, self.w,
            jnp.asarray(ins_s), jnp.asarray(ins_d), jnp.asarray(ins_w),
            jnp.asarray(del_s), jnp.asarray(del_d),
            jnp.int32(self.ne), nv_pad=self.nv_pad,
            accum_dtype=(adt if adt == "ds32" else None))
        fr_d, nfr_d = delta_frontier(
            src2, dst2, jnp.asarray(ins_s), jnp.asarray(ins_d),
            jnp.asarray(del_s), jnp.asarray(del_d), nv_pad=self.nv_pad)
        if self._frontier is not None:
            fr_d = jnp.logical_or(fr_d, self._frontier)
            nfr_d = jnp.sum(fr_d.astype(jnp.int32))
        ne2, del_w, n_hit, n_fr = jax.device_get(
            (ne2_d, del_w_d, nhit_d, nfr_d))
        self.src, self.dst, self.w = src2, dst2, w2
        self.ne = int(ne2)
        # 2m fixup on host, f64: inserts add a mass known exactly from
        # the canonical batch; deletes subtract the retired rows' slab
        # weight as measured by the chokepoint.
        self.tw2 = self.tw2 + ins_mass - float(del_w)
        if self.tw2 <= 0:
            raise ValueError("delta removed the last edge weight; an "
                             "empty graph cannot be re-clustered")
        self.fingerprint = _fold_fingerprint(self.fingerprint,
                                             batch.digest())
        self._frontier = fr_d
        self.frontier_frac = float(int(n_fr)) / float(self.nv)
        self.deltas_applied += 1
        wall = time.perf_counter() - t0
        info = {"n_ins": batch.n_ins, "n_del": batch.n_del,
                "n_del_hit": int(n_hit), "ne": self.ne,
                "frontier_frac": round(self.frontier_frac, 6),
                "wall_s": wall}
        self.tracer.event("delta", **info)
        return info

    # -- re-clustering ------------------------------------------------------

    def _accum(self) -> str:
        from cuvite_tpu.louvain.driver import _accum_name

        return _accum_name(np.dtype(np.float32), self.tw2,
                           max(self.ne, self.nv_pad))

    def recluster(self, warm: str = "labels", threshold: float = 1.0e-6,
                  max_phases: int = TERMINATION_PHASE_COUNT,
                  warm_labels=None, warm_fingerprint: int | None = None,
                  plp_iters: int = 3):
        """Re-cluster the resident slab; returns a
        ``louvain.driver.LouvainResult`` (same shape as the batch
        drivers, so golden envelopes and serving replies apply as-is).

        ``warm='labels'`` seeds phase 0 from the previous run's
        composed labels (or caller-supplied ``warm_labels`` tagged with
        ``warm_fingerprint``) and activates only the accumulated delta
        frontier; a fingerprint mismatch raises.  ``warm='plp'`` seeds
        from a ``plp_iters``-sweep label-propagation prepass;
        ``warm='cold'`` starts from identity.  Both non-label arms
        activate every real vertex.
        """
        from cuvite_tpu.louvain.driver import (
            LouvainResult,
            PhaseStats,
            warm_start_phase,
        )
        from cuvite_tpu.louvain.fused import _fused_step_call, fused_louvain
        from cuvite_tpu.louvain.precise import phase_modularity

        if warm not in WARM_MODES:
            raise ValueError(f"unknown warm-start arm {warm!r}; "
                             f"use one of {WARM_MODES}")
        t0 = time.perf_counter()
        nv, nv_pad = self.nv, self.nv_pad
        adt = self._accum()
        real_mask = self.real_mask
        vdeg = device_weighted_degrees(self.src, self.w, nv_pad=nv_pad)
        constant = jnp.asarray(1.0 / self.tw2, dtype=jnp.float32)

        if warm == "labels":
            labels = warm_labels if warm_labels is not None \
                else self._labels
            fp = warm_fingerprint if warm_labels is not None \
                else self._labels_fp
            if labels is None:
                raise ValueError(
                    "warm-start 'labels' needs resident labels: run a "
                    "cold (or plp) recluster first, or pass warm_labels")
            if fp != self.frontier_base_fp:
                raise ValueError(
                    f"stale warm-start refused: labels carry content "
                    f"fingerprint {fp:#x} but the session's pre-delta "
                    f"lineage is {self.frontier_base_fp:#x} — these "
                    "labels were not computed against the slab the "
                    "pending deltas edited (wrong session, wrong base, "
                    "or a skipped batch); re-cluster cold instead")
            comm0_np = np.arange(nv_pad, dtype=np.int32)
            comm0_np[:nv] = np.asarray(labels, dtype=np.int32)[:nv]
            comm0 = jnp.asarray(comm0_np)
            active0 = (self._frontier & real_mask) \
                if self._frontier is not None \
                else jnp.zeros((nv_pad,), bool)
        elif warm == "plp":
            comm0 = plp_prepass(self.src, self.dst, self.w, vdeg,
                                nv_pad=nv_pad, accum_dtype=adt,
                                iters=int(plp_iters))
            active0 = real_mask
        else:
            comm0 = jnp.arange(nv_pad, dtype=jnp.int32)
            active0 = real_mask

        extra = (self.src, self.dst, self.w, vdeg, constant)
        sid = self.tracer.begin_span("recluster", warm=warm) \
            if hasattr(self.tracer, "begin_span") else None
        labels_d, mod0_d, iters0_d, _ovf, _conv = warm_start_phase(
            extra, comm0, threshold, active0,
            call=_fused_step_call(nv_pad, adt), nv_real=nv)

        # Device coarsen + label composition, then the fused program for
        # every remaining phase — the _run_fused pattern, one level deep
        # (post-phase-0 graphs are coarse).
        dmap, nc_d = device_renumber(labels_d, real_mask, nv_pad=nv_pad)
        comm_all_d = device_compose_labels(
            dmap, labels_d, jnp.arange(nv, dtype=labels_d.dtype))
        acc = adt if adt == "ds32" else None
        csrc, cdst, cw, _dm, _nc, ne2_d = device_coarsen_slab(
            self.src, self.dst, self.w, labels_d, real_mask,
            nv_pad=nv_pad, accum_dtype=acc, dense_map=dmap, nc=nc_d,
            coalesce="sort")
        nc, ne2, mod0, iters0 = jax.device_get(  # graftlint: disable=R010 — phase-scalar sync, O(1), the streaming analog of the fused driver's per-call stat fetch
            (nc_d, ne2_d, mod0_d, iters0_d))
        nc, ne2, iters0 = int(nc), int(ne2), int(iters0)
        csrc, cdst, cw, cnv_pad, cne_pad = maybe_shrink_to_class(
            csrc, cdst, cw, nc=nc, ne2=ne2, nv_pad=nv_pad,
            ne_pad=self.ne_pad)

        phases = [PhaseStats(phase=0, modularity=float(mod0),
                             iterations=iters0, num_vertices=nv,
                             num_edges=self.ne, seconds=0.0)]
        tot_iters = iters0
        mask2 = jnp.arange(cnv_pad, dtype=jnp.int32) < jnp.int32(nc)
        max_p2 = max(int(max_phases) - 1, 1)
        ths = np.full(max_p2, threshold, dtype=np.float32)
        out = fused_louvain(
            csrc, cdst, cw, jnp.asarray(ths), constant, mask2,
            nv_pad=cnv_pad, max_phases=max_p2, accum_dtype=adt,
            cycling=False, prev_mod0=np.float32(mod0))
        labels2 = out[0]
        n_ph2, iters2, mod_hist, iter_hist, nc_hist = jax.device_get(  # graftlint: disable=R010 — phase-scalar sync, O(max_phases)
            (out[2], out[3], out[4], out[5], out[6]))
        n_ph2, iters2 = int(n_ph2), int(iters2)
        tot_iters += iters2
        nv_p = nc
        for p in range(n_ph2):
            phases.append(PhaseStats(
                phase=len(phases), modularity=float(mod_hist[p]),
                iterations=int(iter_hist[p]), num_vertices=nv_p,
                num_edges=ne2, seconds=0.0))
            nv_p = int(nc_hist[p])
        dmap2, nc2_d = device_renumber(labels2, mask2, nv_pad=cnv_pad)
        comm_all_d = device_compose_labels(dmap2, labels2, comm_all_d)
        comm_all = np.asarray(comm_all_d).astype(np.int64)  # graftlint: disable=R010 — the final label gather, O(V), same allowlist as the fused driver's
        num_comms = int(comm_all.max()) + 1 if comm_all.size else 0

        dgq = DistGraph.from_device_slab(
            csrc, cdst, cw, num_vertices=nc, num_edges=ne2,
            nv_pad=cnv_pad, ne_pad=cne_pad, policy=self.policy,
            total_weight_twice=self.tw2)
        final_q = phase_modularity(dgq, np.asarray(labels2),  # graftlint: disable=R010 — final labels, O(coarse V), re-used on device by the ds pass
                                   device_slab=(csrc, cdst, cw))

        wall = time.perf_counter() - t0
        for st in phases:
            st.seconds = wall / max(len(phases), 1)
        # Labels now describe the CURRENT content; the frontier resets.
        self._labels = comm_all
        self._labels_fp = self.fingerprint
        self.frontier_base_fp = self.fingerprint
        self._frontier = None
        frontier_frac = self.frontier_frac
        self.frontier_frac = 0.0
        if sid is not None:
            self.tracer.end_span(sid, wall_s=wall, warm=warm,
                                 q=float(final_q),
                                 frontier_frac=round(frontier_frac, 6),
                                 iterations=tot_iters)
        else:
            self.tracer.event("recluster", warm=warm, wall_s=wall,
                              q=float(final_q), iterations=tot_iters)
        return LouvainResult(
            communities=comm_all, modularity=float(final_q),
            phases=phases, total_iterations=tot_iters,
            total_seconds=wall, convergence=[])
