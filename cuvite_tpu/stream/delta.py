"""Delta ingestion against the resident device slab (ISSUE 17).

Live graphs mutate between requests; rebuilding the CSR + re-uploading
the slab per update throws away the device residency the fused driver
works to keep (coarsen/device.py).  This module applies validated edge
insert/delete batches to the slab **in HBM** through ONE jitted
chokepoint:

  * :class:`DeltaBatch` — a canonicalized edit batch: symmetrized like
    ``Graph.from_edges`` (each undirected insert lands as (u,v) and
    (v,u), self-loops once), duplicate inserts coalesced, deletes
    deduped, rows in ascending (src, dst) order.  Canonical form makes
    the batch — and therefore the content fingerprint lineage the
    warm-start validation hangs off — deterministic in the edit
    MULTISET, not the arrival order.
  * :func:`apply_delta_slab` — THE chokepoint (graftlint R029 keeps
    every other resident-slab mutation out of ``stream/``/``serve/``):
    deletes are located by a pure-int32 lexicographic binary search
    over the sorted slab and sentinel-retired in place (src -> nv_pad,
    w -> 0 — exactly a padding row); inserts are masked-appended into
    the slab's padding headroom at traced offset ``ne``; then the whole
    slab re-canonicalizes through the segmented-coalesce chokepoint
    (ops/segment.py::coalesced_runs, sort engine), whose output
    contract — ascending (src, dst), duplicates summed, compacted,
    sentinel padding after — is bit-identical to what
    ``DistGraph.build`` derives from ``Graph.from_edges`` on the
    mutated edge list.  That identity is what the delta-vs-rebuild
    suite pins (tests/test_stream.py).

The pow2 slab class is preserved: the compile key set stays {(nv_pad,
ne_pad, d_pad, accum)}, all pow2, so a tenant's second same-class delta
re-enters the compiled program with zero fresh traces.  When an insert
batch overflows the padding headroom the HOST wrapper (stream/
session.py) first lifts the slab to the next pow2 class via
``coarsen.device.grow_slab`` — the spill twin of ``shrink_slab`` —
never by a dynamic reshape inside the jit.

Exactness domain: duplicate-weight sums run through the same
accumulators as coarsening, so slab weights match the host rebuild
bit-for-bit wherever run sums are exactly representable (unit/dyadic
weights — the parity suite's domain, cf. coarsen/device.py).
"""

from __future__ import annotations

import dataclasses
import functools
import zlib

import jax
import jax.numpy as jnp
import numpy as np

from cuvite_tpu.ops import segment as seg

# Floor on the padded delta-batch class: batches pad to
# max(next_pow2(n), DELTA_PAD_MIN) so every small batch shares one
# compiled chokepoint instance per slab class instead of one per size.
DELTA_PAD_MIN = 256


def _canon_pairs(src, dst, nv: int, what: str):
    """Validate + symmetrize an edit pair list: int64 arrays, ids in
    [0, nv); (u, v) with u != v contributes both directions, a self-loop
    once — exactly Graph.from_edges' symmetrize convention."""
    src = np.asarray(src, dtype=np.int64).ravel()
    dst = np.asarray(dst, dtype=np.int64).ravel()
    if src.shape != dst.shape:
        raise ValueError(f"{what}: src/dst length mismatch "
                         f"({src.size} vs {dst.size})")
    if src.size and (src.min() < 0 or dst.min() < 0
                     or src.max() >= nv or dst.max() >= nv):
        raise ValueError(
            f"{what}: vertex id out of range [0, {nv}) — streaming "
            "deltas mutate edges among the session's existing vertices")
    off = src != dst
    return (np.concatenate([src, dst[off]]),
            np.concatenate([dst, src[off]]), off)


@dataclasses.dataclass(frozen=True)
class DeltaBatch:
    """One canonical edge edit batch against an ``nv``-vertex graph.

    ``ins_src``/``ins_dst``/``ins_w``: coalesced symmetrized inserts in
    ascending (src, dst) order; ``del_src``/``del_dst``: deduped
    symmetrized deletes, same order.  Deletes apply to the BASE slab
    first, inserts after — so the rebuild oracle for a batch is
    ``(base_edges - deletes) + inserts``.
    """

    num_vertices: int
    ins_src: np.ndarray
    ins_dst: np.ndarray
    ins_w: np.ndarray
    del_src: np.ndarray
    del_dst: np.ndarray

    @property
    def n_ins(self) -> int:
        return int(self.ins_src.size)

    @property
    def n_del(self) -> int:
        return int(self.del_src.size)

    @staticmethod
    def from_edits(num_vertices: int, ins_src=(), ins_dst=(), ins_w=None,
                   del_src=(), del_dst=()) -> "DeltaBatch":
        nv = int(num_vertices)
        if nv <= 0:
            raise ValueError("num_vertices must be positive")
        isrc, idst, off = _canon_pairs(ins_src, ins_dst, nv, "inserts")
        n_in = off.size                       # original (pre-mirror) pairs
        if ins_w is None:
            w = np.ones(isrc.shape, dtype=np.float64)
        else:
            # Weights are given per INPUT pair; mirror like the pairs.
            w0 = np.asarray(ins_w, dtype=np.float64).ravel()
            if w0.size != n_in:
                raise ValueError(f"inserts: weight length mismatch "
                                 f"({w0.size} weights, {n_in} pairs)")
            w = np.concatenate([w0, w0[off]])
        if w.size and (not np.all(np.isfinite(w)) or np.any(w < 0)):
            raise ValueError("inserts: weights must be finite and >= 0")
        # Coalesce duplicate insert pairs (sum in f64, like from_edges)
        # and land in ascending (src, dst) order.
        if isrc.size:
            key = isrc * nv + idst
            order = np.argsort(key, kind="stable")
            key, isrc, idst, w = key[order], isrc[order], idst[order], \
                w[order]
            first = np.concatenate([[True], key[1:] != key[:-1]])
            seg_id = np.cumsum(first) - 1
            wsum = np.zeros(int(seg_id[-1]) + 1, dtype=np.float64)
            np.add.at(wsum, seg_id, w)
            isrc, idst, w = isrc[first], idst[first], wsum
        dsrc, ddst, _ = _canon_pairs(del_src, del_dst, nv, "deletes")
        if dsrc.size:
            key = dsrc * nv + ddst
            key = np.unique(key)
            dsrc, ddst = key // nv, key % nv
        return DeltaBatch(
            num_vertices=nv,
            ins_src=isrc.astype(np.int64), ins_dst=idst.astype(np.int64),
            ins_w=w.astype(np.float64),
            del_src=dsrc.astype(np.int64), del_dst=ddst.astype(np.int64))

    def digest(self) -> int:
        """Content digest of the canonical batch — folded into the
        session's fingerprint lineage (stream/session.py), so a
        warm-start against labels from a different edit history is
        refused by arithmetic, not by convention."""
        h = zlib.crc32(np.ascontiguousarray(self.ins_src).view(np.uint8))
        h = zlib.crc32(np.ascontiguousarray(self.ins_dst).view(np.uint8), h)
        h = zlib.crc32(np.ascontiguousarray(self.ins_w).view(np.uint8), h)
        h = zlib.crc32(np.ascontiguousarray(self.del_src).view(np.uint8), h)
        h = zlib.crc32(np.ascontiguousarray(self.del_dst).view(np.uint8), h)
        return h

    def padded(self, d_pad: int | None = None):
        """Device-ready pow2-padded operand arrays for
        :func:`apply_delta_slab` — pad rows carry id -1 (the chokepoint
        masks them).  One pow2 ``d_pad`` class per batch size keeps the
        compile-key set bounded."""
        from cuvite_tpu.core.types import next_pow2

        if d_pad is None:
            d_pad = max(next_pow2(max(self.n_ins, self.n_del, 1)),
                        DELTA_PAD_MIN)

        def pad_ids(a):
            out = np.full(d_pad, -1, dtype=np.int32)
            out[:a.size] = a
            return out

        iw = np.zeros(d_pad, dtype=np.float32)
        iw[:self.n_ins] = self.ins_w
        return (pad_ids(self.ins_src), pad_ids(self.ins_dst), iw,
                pad_ids(self.del_src), pad_ids(self.del_dst), d_pad)


def _lex_search(src, dst, q_src, q_dst, *, ne_pad: int):
    """First slab index whose (src, dst) row is >= each query pair,
    by a vectorized lexicographic binary search — pure int32 (the
    packed-key trick would need int64 beyond nv_pad ~2^15; R003 keeps
    64-bit dtypes off the device path)."""
    lo = jnp.zeros(q_src.shape, jnp.int32)
    hi = jnp.full(q_src.shape, ne_pad, jnp.int32)

    def body(_, c):
        lo, hi = c
        mid = (lo + hi) >> 1
        ms = jnp.take(src, mid).astype(jnp.int32)
        md = jnp.take(dst, mid).astype(jnp.int32)
        less = (ms < q_src) | ((ms == q_src) & (md < q_dst))
        return jnp.where(less, mid + 1, lo), jnp.where(less, hi, mid)

    steps = max(ne_pad.bit_length(), 1)  # ne_pad is a static python int
    lo, _ = jax.lax.fori_loop(0, steps, body, (lo, hi))
    return lo


@functools.partial(jax.jit, static_argnames=("nv_pad", "accum_dtype"))
def apply_delta_slab(src, dst, w, ins_src, ins_dst, ins_w, del_src,
                     del_dst, ne, *, nv_pad: int, accum_dtype=None):
    """THE resident-slab mutation chokepoint (see module docstring).

    ``src``/``dst``/``w``: the [ne_pad] canonical slab (ascending
    (src, dst), coalesced, padding src == nv_pad / dst == 0 / w == 0
    after the first ``ne`` rows).  ``ins_*``/``del_*``: [d_pad]
    canonical batch operands from :meth:`DeltaBatch.padded` (pad rows
    id == -1).  ``ne``: traced real-row count.

    Returns ``(src2, dst2, w2, ne2, del_w, n_del_hit)``: the mutated
    slab back in canonical form in the SAME [ne_pad] class, its new
    real-row count, the total weight of retired rows (the host's 2m
    fixup subtracts it; inserts add their own known mass), and how many
    deletes matched a resident edge (absent-edge deletes are no-ops,
    exactly like the rebuild oracle's set difference).
    """
    vdt = src.dtype
    wdt = w.dtype
    ne_pad = src.shape[0]

    # --- deletes: locate + sentinel-retire --------------------------------
    q_valid = del_src >= 0
    qs = jnp.where(q_valid, del_src, jnp.int32(nv_pad))
    qd = jnp.where(q_valid, del_dst, 0)
    pos = _lex_search(src, dst, qs, qd, ne_pad=ne_pad)
    pos_c = jnp.minimum(pos, ne_pad - 1)
    hit = q_valid & (jnp.take(src, pos_c).astype(jnp.int32) == qs) \
        & (jnp.take(dst, pos_c).astype(jnp.int32) == qd)
    del_w = jnp.sum(jnp.where(hit, jnp.take(w, pos_c),
                              jnp.zeros((), wdt)))
    n_del_hit = jnp.sum(hit.astype(jnp.int32))
    retire_at = jnp.where(hit, pos_c, ne_pad)     # ne_pad drops
    src = src.at[retire_at].set(
        jnp.full(retire_at.shape, nv_pad, vdt), mode="drop")
    dst = dst.at[retire_at].set(
        jnp.zeros(retire_at.shape, vdt), mode="drop")
    w = w.at[retire_at].set(jnp.zeros(retire_at.shape, wdt), mode="drop")

    # --- inserts: masked append into the padding headroom -----------------
    i_valid = ins_src >= 0
    slot = jnp.where(i_valid,
                     ne.astype(jnp.int32) + jnp.arange(
                         ins_src.shape[0], dtype=jnp.int32),
                     jnp.int32(ne_pad))
    src = src.at[slot].set(
        jnp.where(i_valid, ins_src, nv_pad).astype(vdt), mode="drop")
    dst = dst.at[slot].set(
        jnp.where(i_valid, ins_dst, 0).astype(vdt), mode="drop")
    w = w.at[slot].set(
        jnp.where(i_valid, ins_w.astype(wdt), jnp.zeros((), wdt)),
        mode="drop")

    # --- re-canonicalize through the coalesce chokepoint ------------------
    src2, dst2, w2, ne2 = seg.coalesced_runs(
        src, dst, w, nv_pad=nv_pad, accum_dtype=accum_dtype,
        engine="sort")
    return src2, dst2, w2.astype(wdt), ne2, del_w, n_del_hit


@functools.partial(jax.jit, static_argnames=("nv_pad",))
def delta_frontier(src, dst, ins_src, ins_dst, del_src, del_dst, *,
                   nv_pad: int):
    """Warm-start active set of a delta: the touched endpoints (every
    insert/delete endpoint) plus their slab neighbors — the vertices
    whose best-community argmax could have changed — instead of "all"
    (cf. the ET active-set semantics, louvain/driver.py).  Runs on the
    POST-apply slab, so inserted edges propagate and retired rows do
    not.  Returns ``(frontier [nv_pad] bool, n_frontier)``."""
    touched = jnp.zeros((nv_pad,), bool)
    for a in (ins_src, ins_dst, del_src, del_dst):
        idx = jnp.where(a >= 0, a, jnp.int32(nv_pad))
        touched = touched.at[idx].set(True, mode="drop")
    pad = src >= nv_pad
    s_c = jnp.minimum(src, nv_pad - 1).astype(jnp.int32)
    d_c = dst.astype(jnp.int32)
    hot = (jnp.take(touched, s_c) | jnp.take(touched, d_c)) & ~pad
    fr = touched
    fr = fr.at[jnp.where(hot, s_c, nv_pad)].set(True, mode="drop")
    fr = fr.at[jnp.where(hot, d_c, nv_pad)].set(True, mode="drop")
    return fr, jnp.sum(fr.astype(jnp.int32))


@functools.partial(jax.jit, static_argnames=("nv_pad", "accum_dtype",
                                             "iters"))
def plp_prepass(src, dst, w, vdeg, *, nv_pad: int, accum_dtype=None,
                iters: int = 3):
    """PLP label-propagation prepass (Staudt & Meyerhenke,
    arXiv:1304.4453 — PAPERS.md): ``iters`` synchronous sweeps of the
    Louvain step with ``constant = 0``, under which the gain degenerates
    to ``2*(e_{i->y} - e_{i->x})`` — adopt the neighbor community with
    the largest incident weight, ties to the smaller id.  The cheap
    cold-start alternative the ``--warm-start plp`` arm A/Bs against
    composed-label seeding."""
    from cuvite_tpu.louvain.step import louvain_step_local

    comm0 = jnp.arange(nv_pad, dtype=jnp.int32)
    zero = jnp.zeros((), w.dtype)

    def body(_, comm):
        out = louvain_step_local(
            src, dst, w, comm, vdeg, zero, nv_total=nv_pad,
            axis_name=None, accum_dtype=accum_dtype)
        return out.target

    return jax.lax.fori_loop(0, iters, body, comm0)
