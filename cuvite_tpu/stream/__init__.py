"""Streaming subsystem: delta ingestion + warm-start incremental
re-clustering for live graphs (ISSUE 17).

``DeltaBatch`` canonicalizes edge insert/delete batches;
``apply_delta_slab`` is THE jitted chokepoint that mutates the resident
device slab (graftlint R029 forbids slab mutation anywhere else in
stream/ and serve/); ``StreamSession`` owns a tenant's resident slab
and runs warm-start re-clustering seeded from the previous labels and
the delta frontier.
"""

from cuvite_tpu.stream.delta import (
    DELTA_PAD_MIN,
    DeltaBatch,
    apply_delta_slab,
    delta_frontier,
    plp_prepass,
)
from cuvite_tpu.stream.session import WARM_MODES, StreamSession

__all__ = [
    "DELTA_PAD_MIN",
    "DeltaBatch",
    "StreamSession",
    "WARM_MODES",
    "apply_delta_slab",
    "delta_frontier",
    "plp_prepass",
]
